"""Fused Pallas TPU kernel for the alignment search (reference C13+C14,
re-designed TPU-first).

The XLA matmul path materialises the pair-value matrix V, its sheared
diagonals and their prefix sums in HBM (~4 full [L2P, W] arrays per pair);
profiling shows those HBM round-trips dominate.  This kernel fuses the whole
delta-formulation pipeline so V never leaves VMEM:

  per pair (two pairs share one grid cell, amortising per-cell
  overhead), per (offset-block nb, char-block ib) 128x128 tile:
    onehot(seq2 block)            [128, 128]   broadcast compare, VPU
    V tile = onehot @ A band      [128, 256]   MXU (A = val @ onehot(seq1).T,
                                               rows padded 27 -> 128, stored
                                               lane-REVERSED)
    shear row r left by r         ONE tpu.dynamic_rotate with stride=1 over
                                               the row axis.  Mosaic's
                                               strided rotate only turns one
                                               direction (and shifts by the
                                               full row index — measured, no
                                               mod-128 wrap), so the kernel
                                               runs in reversed lane
                                               orientation end to end (A
                                               pre-reversed host-side; the
                                               in-kernel argmax maps lanes
                                               back to offsets)
    block prefix                  narrow feeds: ltri128 @ d0 - ltri128 @ d1
                                  (two bf16 MXU matmuls; the all-ones row
                                  127 of ltri@d1 doubles as the t1 sublane
                                  sum, so the dd subtract and the t1 VPU
                                  reduction disappear); f32 feed: one
                                  ltri128 @ (d0-d1) matmul + VPU t1 sum
                                  (f32 MXU is ~8x slower, the extra matmul
                                  would not pay)
    streaming carries             prefix carry, running (max, first-kappa),
                                  G[len2] capture, t1 totals — all lane
                                  vectors in registers

  outputs per pair: ONE best candidate [score, n, k, eq] — the offset
  masking and argmax run in-kernel on cheap [1, sbw] lane vectors
  (round 1 wrote three [B, W] reversed surfaces instead; the XLA
  un-reverse + argmax epilogue cost ~33 us/call on input3, ~17%); only
  the O(B)-scalar equal-length / unsearchable selection stays in XLA.

Tie-break parity with the reference's offset-major, k-ascending-with-0-first
order (cudaFunctions.cu:161) is preserved: strictly-greater running updates
keep the smallest kappa, first-hit row selection uses a min-index reduction,
and k=0 (kappa = len2) outranks equal-scoring k >= 1 via the G[len2]
capture.  Float32 math is exact for |weight| <= max_exact_value(l2p) —
the length-aware bound shared with the matmul path (4095 for the padded
2048-row buckets, up to 32767 at l2p = 128; f32-feed matmuls run
Precision.HIGHEST because TPU MXUs multiply f32 at bf16 precision by
default — see ops/matmul_scorer.py); the module transparently falls
back to the XLA bodies for larger weights
or for shape buckets that are not 128-aligned (e.g. the tiny-shape
multi-chip dryrun).

Two workload-adaptive fast paths on top of the baseline kernel:

* **offset-block skip** — a pair only has valid offsets n < len1 - len2,
  so offset blocks wholly past that bound are skipped per pair (the
  epilogue masks their lanes anyway).  For near-Seq1-length sequences this
  removes most of the grid; block nb=0 always runs because it carries the
  equal-length k=0 capture.
* **narrow MXU feeds** — ``mxu_feed`` picks the fastest exact operand
  type per value table.  |v| <= 127: the one-hot matmul runs int8 x int8
  with int32 accumulation (exact by construction, the MXU's fastest
  path).  |v| <= 128: bfloat16 operands with float32 accumulation —
  exact because one-hot factors are 0/1, V entries are integers
  |v| <= 128, the delta d0-d1 is an integer of magnitude <= 256 (every
  integer up to 2^8 is exactly representable in bf16's 8 mantissa bits),
  and float32 partial sums stay below 2^24.  The delta (ltri) matmul
  runs bf16 on both narrow feeds; larger weights keep the f32 kernel.

Explored and rejected (r2, measured/attempted on the real chip — do not
re-litigate without new Mosaic capabilities):

* an int8 DELTA formulation for |v| <= 63 (lp = ltri @ (d0 - d1) as one
  int8 matmul + a thin ones-row t1 matmul, ~47% fewer prefix MACs) —
  Mosaic cannot legalize int8 vector subtraction (`arith.subi` on i8),
  and routing the subtract through i32/bf16 costs 2-3 extra full-width
  VPU passes, erasing the saved matmul.  The pa - pb split with the
  all-ones-row t1 capture is the local optimum under that constraint.
  r3 re-test with the subtract in INT32 (before one narrow cast + a
  single prefix matmul): measured -38% (input3) / -40% (max-size) —
  rejected in every legal form.
* casting before the shear — the strided rotate only exists for 32-bit
  element types ("Rotate with non-32-bit data: not implemented").
* 4-wide tile interleave — VMEM pressure regresses it ~5% vs 2-wide.
  3-wide: read +3.7% on input3 in one sequential A/B, within the
  co-tenant noise band on re-measurement; not adopted.  (Same lesson as
  the pp=1 episode: only interleaved A/Bs count on this shared chip.)
* one-hot contraction-zero packing (VERDICT r2 item 4: 27 of 128 K
  lanes live, pack 4 char blocks as 4x32 block-diagonal segments) —
  cannot win: MXU time is M*K*N regardless of K-lane zeros, so packing
  4 blocks with DISJOINT output lanes multiplies N by 4 (identical
  total MACs to 4 separate tiles), while SHARED output lanes sum the 4
  tiles' V values, destroying the per-char prefix/kappa resolution.
  The r3 ablation confirms no headroom exists there anyway: removing
  the one-hot matmul entirely saves only 2.8% (input3) / 9.5%
  (max-size) — the kernel is VPU-pass-bound, not MAC-bound.
* int32 prefix matmuls (skip the cast entirely) — Mosaic compile error:
  int32 matmul is not legalizable.
* a second base-1 strided rotate to 128-align the d1 operand — the
  extra rotate costs more than the misaligned-slice copy it removes
  (measured -33%).
* deferring the packed row-max across the 2-wide tiles (one reduction
  per iteration) — measured +-0; the reduction is not the bottleneck
  pass, and with carryfold the carry re-injection per tile is needed
  anyway.
* narrowing the int32->int8 cast to the consumed union slice
  [127, sbw+128) (~8% less cast area) — does not reproduce across
  interleaved passes (+2.8/-5.7%): the misaligned slice source costs
  the realignment what the area saves.

Adopted r4: **row packing** (`_kernel_packed`) — single-char-block
buckets whose every pair has len2 <= 64 pack p = 128/l2s pairs per
tile.  The affine strided rotate gives each l2s-row segment a uniform
extra rotation of j*l2s, so segment diagonals land CYCLICALLY permuted
in the lane axis; with a block-diagonal ltri and the prefix matmul run
over the full W = sbw+128 lanes (ONE matmul — prefix commutes with the
lane shift, so prefix(d1) = roll(prefix(d0), 1 lane); the d1-adjacency
seam sits at offsets >= n0+sbw+128-l2s, outside the per-block window —
cell-verified in scripts/rowpack_proto.py), every (segment, offset,
kappa) cell is exact.  The per-lane argmax packs an offset-ORDER key
(sbw-1-(n-n0)) instead of the raw lane index to keep the reference
first-hit tie-break.  input4: 40-56 us gated across records vs r3's
75.1 us (+34-87% throughput; dispatch-floor noise dominates the spread
at this size); packable-subset interleaved A/B reads packed 1.8-3.2x
unpacked.  Dispatch buckets rows into packing classes so a long
straggler splits off instead of blocking the batch
(ops/dispatch.py::plan_buckets / choose_rowpack).

Extended r6: row packing serves EVERY feed, not just i8 — the packed
matmuls run in the feed dtype and the prefix result is cast to int32
before the integer argmax-key packing, which stays exact while
3 * l2s * maxv < 2^19 (``dispatch.pack_classes``): i8 and bf16 keep
all four classes {8, 16, 32, 64}; f32 keeps the classes its measured
maxv affords (all four to |v| <= 2730, {8, 16, 32} through the static
4095 bound, {8, 16} to 10922, {8} to 21845).  Gated A/B on a
64-pair len2 <= 8 batch at |v| = 3000: packed f32 2.1x unpacked f32
(the same structural win as i8's 1.8-3.2x, minus the HIGHEST matmul
multiplier that both arms pay).
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.constants import ALPHABET_SIZE, INT32_MIN
from .bounds import INT32_PACKED_SENTINEL, PACK_RADIX, PACKED_L2P_CEILING

_BLK = 128
# Plain Python scalars: jnp scalars would be captured as pallas kernel
# constants, which pallas_call rejects.
_NEG = -(2.0**40)
_BIGROW = 1 << 30

# |pair value| bound below which feeding the MXU in bfloat16 stays exact
# (see module docstring); checked on concrete weights at dispatch time.
MAX_BF16_EXACT_WEIGHT = 128
# int8 range: with |v| <= 127 the one-hot matmul runs as int8 x int8 with
# int32 accumulation — exact by construction and the MXU's fastest feed.
MAX_I8_EXACT_WEIGHT = 127

_FEED_DTYPES = {"i8": jnp.int8, "bf16": jnp.bfloat16, "f32": jnp.float32}

# The pre-r6 1-wide f32 walk is selectable per call via the ``wide1``
# STATIC argument of ``score_chunks_pallas`` (threaded down to _kernel);
# scripts/f32_bench.py's F32_AB=wide arm passes ``wide1=True``.  It used
# to be a module-level flag (``_F32_WIDE1_AB``) flipped around
# ``_pallas_call.cache_clear()`` — bench-only mutable state that could
# leak a stale jit trace into production dispatch; as a static argument
# both variants key their own cache entries and coexist safely.


def mxu_feed(val_flat) -> str:
    """Fastest exact MXU operand type for this value table: 'i8' (int8
    operands, int32 accumulation) when |v| <= 127, 'bf16' (bf16 operands,
    f32 accumulation) at exactly 128, 'f32' otherwise (up to the matmul
    path's length-aware ``max_exact_value(l2p)`` bound — 4095 for padded
    2048-row buckets, up to 32767 at l2p = 128; beyond that dispatch
    routes to the gather body)."""
    from .values import max_abs_value

    m = max_abs_value(val_flat)
    if m <= MAX_I8_EXACT_WEIGHT:
        return "i8"
    if m <= MAX_BF16_EXACT_WEIGHT:
        return "bf16"
    return "f32"


def _superblock(nbn: int) -> int:
    """Static-fallback offset-super-block width (used when the batch's
    concrete lengths are unavailable — bench tooling, abstract traces).
    Adjacent offset blocks share all but 128 of their A-band columns, so a
    wider super-block cuts the one-hot matmul's MACs (band width
    (SB+1)*128 instead of SB*2*128) and amortises per-iteration overhead.
    Bounded at 12: measured on the real chip, widening 6->12 (input3) and
    8->12 (max-size synthetic) won 5%/15%."""
    for cand in (12, 8, 6, 4, 2):
        if nbn % cand == 0:
            return cand
    return 1


# Adaptive-width cost model, refit on the SHIPPED r3/r4 kernel
# (scripts/sb_refit.py, 2026-07-31: interleaved sweeps over five
# workload classes with amortisation scaled per class — the r2-era
# constants predated tail1/wide1 and the sb=24 widening, VERDICT r3
# item 6): one loop iteration costs the larger of an affine floor (loop
# + rotate latency + VPU reductions, growing with the band width) and
# its MAC issue time at the effective mixed i8/i32 rate.  The refit
# (least squares with a per-workload call-overhead nuisance, log-err
# 0.025) reproduces every measured winner exactly (max-size sb=12,
# input4-class sb=24 unpacked AND packed) or within a <=10% wall tie
# (input3-class 12 vs measured 6: 191.6 vs 187.3 us; skew 2 vs measured
# 3: 464.4 vs 431.7 us).
_ITER_FLOOR_BASE_S = 0.70e-6
_ITER_FLOOR_PER_SB_S = 0.040e-6
_MAC_RATE = 112e12  # MACs/s, mixed one-hot i8 + int8 prefix stages

# bf16-feed constants (r6: scripts/sb_refit.py SB_FEED=bf16, interleaved
# sweeps at |w| = 128 over the same five workload classes).  The pre-r6
# chooser ALIASED the i8 constants on argument alone; the gated refit
# confirms the structural claim behind the alias at the WINNER level —
# but not at the constant level: the honest bf16 MXU rate is ~half the
# int8 rate, and the per-sb floor slope fits ~3x the i8 slope (the
# f32->bf16 narrowing casts on the shear operand scale with the band
# width, where i8 narrows once into the one-hot).  Log-err 0.031; every
# winner matches the i8 chooser's pick on the swept grids, so the alias
# was RIGHT, and is now measured rather than asserted.
_ITER_FLOOR_BASE_BF16_S = 0.75e-6
_ITER_FLOOR_PER_SB_BF16_S = 0.13e-6
_MAC_RATE_BF16 = 58e12

# f32-feed constants (r5: scripts/f32_bench.py, probe-gated interleaved
# sb sweeps over three workload classes on the real chip — VERDICT r4
# item 4; the old chooser PUNTED to the static policy for f32, which a
# skew-class sweep measured at 2.63x over the per-batch best.  REFIT r6
# under the 2-wide walk after the f32 interleave landed — the r5 fit
# priced the old wide1 walk, and the model must match the walk it
# prices).  Grid fit with a per-class call-overhead nuisance, log-err
# 0.038 (r5's wide1 fit was 0.041): the f32 kernel still pays ~4x the
# i8 per-tile MAC time and a much heavier iteration floor (f32 one-hot
# + f32 prefix surfaces), but the 2-wide interleave hides more of the
# per-iteration floor under the slow f32 MACs, which the refit absorbs
# as a higher effective MAC rate with a steeper per-sb floor slope
# (the f32 rotate/select surfaces DON'T pipeline, and double at 2-wide).
# The fit reproduces the measured winners on max-size (sb=12) and skew
# (sb=2) exactly and keeps the input3-class pick inside the measured
# 3..6 shallow bowl (<=10% wall ties; fitted pick sb=6).
_ITER_FLOOR_BASE_F32_S = 0.90e-6
_ITER_FLOOR_PER_SB_F32_S = 0.40e-6
_MAC_RATE_F32 = 28e12

# Per-feed (base, per_sb, rate) for the chooser; see the blocks above.
_SB_CONSTANTS = {
    "i8": (_ITER_FLOOR_BASE_S, _ITER_FLOOR_PER_SB_S, _MAC_RATE),
    "bf16": (_ITER_FLOOR_BASE_BF16_S, _ITER_FLOOR_PER_SB_BF16_S, _MAC_RATE_BF16),
    "f32": (_ITER_FLOOR_BASE_F32_S, _ITER_FLOOR_PER_SB_F32_S, _MAC_RATE_F32),
}


def model_constants(feed: str) -> tuple[float, float, float]:
    """``(iteration-floor base s, per-sb floor slope s/sb, MAC rate
    MACs/s)`` of the calibrated super-block cost model for ``feed`` —
    the public read-only view for the analysis layer
    (``analysis.costmodel`` prices whole schedules with the SAME
    constants the chooser minimises, so chooser refits automatically
    re-price the schedule prediction)."""
    return _SB_CONSTANTS[feed]


def _live_superblocks(nbn: int, sb: int, len1: int, l2: int) -> int:
    """Number of offset super-blocks the kernel executes for one pair:
    block 0 always runs; block j*sb (j >= 1) runs while j*sb*128 <
    len1 - l2.  Closed form of the kernel's ``nb == 0 or n0 < len1 - l2``
    loop gate (ADVICE r2: the generator form was O(nbn/sb) per pair per
    candidate, material host latency on unbounded ring grids)."""
    jmax = -(-nbn // sb) - 1  # last super-block index
    lim = len1 - l2
    if lim <= 0 or jmax <= 0:
        return 1
    return 1 + min(jmax, (lim - 1) // (sb * _BLK))


def choose_superblock(nbn: int, nbi: int, len1: int, lens, feed: str) -> int:
    """Adaptive offset-super-block width from the batch's length mix
    (VERDICT r1 item 4).

    Wide super-blocks amortise per-iteration overhead but compute every
    offset lane in the block even when the pair's valid range
    n < len1 - len2 covers almost none of them (a near-Seq1-length batch
    wastes ~96% of lane work at sb=12; measured 1.3x slower than sb=2).
    Narrow super-blocks skip dead blocks per pair but pay the iteration
    floor more often.  Minimise the measured cost model over nbn's
    divisors; concrete ``lens`` required (dispatch-time decision)."""
    # Per-feed constant sets (_SB_CONSTANTS): i8's r4 refit, bf16's r6
    # refit (confirming — with numbers — the structural claim behind the
    # old i8 alias), f32's r6 refit under the 2-wide walk.
    # Bounded cache key (ADVICE r3): the cost model consumes lens only
    # through ceil(l2/128) (live char-blocks) and len1 - l2 at sb*128
    # granularity (live super-blocks), so a histogram of lens rounded UP
    # to 128-multiples carries all the signal; the raw multi-thousand-
    # element tuple made large streaming batches store big keys that
    # mostly missed.  Rounding up can undercount live super-blocks by at
    # most one per pair — noise at the model's calibration accuracy.
    hist: dict[int, int] = {}
    for l2 in lens:
        l2 = int(l2)
        if l2 <= 0:
            continue
        l2r = -(-l2 // _BLK) * _BLK
        hist[l2r] = hist.get(l2r, 0) + 1
    return _choose_superblock_cached(
        nbn, nbi, len1, tuple(sorted(hist.items())), feed
    )


def superblock_model_cost(
    nbn: int,
    nbi: int,
    len1: int,
    lens_hist,
    sb: int,
    *,
    base: float = None,
    per_sb: float = None,
    rate: float = None,
    wide1: bool = False,
) -> float:
    """THE super-block cost model for one batch at width ``sb`` —
    the single structural source shared by the dispatch-time chooser and
    the offline refit (scripts/sb_refit.py): a kernel reformulation that
    changes the cost structure must change it HERE, or the next refit
    would silently fit the old structure (r4 code review).

    ``lens_hist`` is an iterable of (l2, count); constants default to
    the shipped calibration and are overridable for fitting."""
    base = _ITER_FLOOR_BASE_S if base is None else base
    per_sb = _ITER_FLOOR_PER_SB_S if per_sb is None else per_sb
    rate = _MAC_RATE if rate is None else rate
    sbw = sb * _BLK
    tile_macs = _BLK * _BLK * (sbw + _BLK) + 2 * _BLK * _BLK * sbw
    floor = base + sb * per_sb
    t_iter2 = max(floor, 2 * tile_macs / rate)
    t_iter1 = max(floor, tile_macs / rate)
    # Mirrors the kernel's walk: 2-wide even part + a 1-wide tail for
    # odd tile counts; wide=1 throughout for single-char-block buckets
    # only (the kernel's r6 gate is `nbi == 1` — every feed interleaves
    # now).  ``wide1`` remains for pricing the pre-r6 f32 walk in A/B
    # tooling (scripts/f32_bench.py); the shipped chooser never sets it.
    # The model must match the walk it prices or the next refit silently
    # fits the wrong structure.
    wide = 1 if wide1 or nbi == 1 else 2
    cost = 0.0
    for l2, count in lens_hist:
        nbi_live = min(-(-int(l2) // _BLK), nbi)
        if wide == 1:
            t_pair = nbi_live * t_iter1
        else:
            t_pair = (nbi_live // 2) * t_iter2 + (nbi_live % 2) * t_iter1
        cost += count * _live_superblocks(nbn, sb, len1, int(l2)) * t_pair
    return cost


@functools.lru_cache(maxsize=1024)
def emittable_superblocks(nbn: int, nbi: int, feed: str) -> tuple[int, ...]:
    """Every super-block width the chooser may emit for this config,
    ascending: the divisors of nbn in [2, 24] that pass the static VMEM
    feasibility gate (analysis.vmem — PR 2's unmeasured-spill bug class
    turned into arithmetic: the wide-walk working set at sb >= 20 models
    over the 16 MiB per-core budget for the wider feeds), plus the
    static ``_superblock`` fallback and the degenerate sb = 1.  Single
    source of truth for BOTH the chooser's candidate list and the
    exhaustive audit sweep (analysis.vmem.iter_chooser_space), so they
    cannot drift."""
    from ..analysis.vmem import fits_budget

    divs = [
        sb
        for sb in range(2, min(nbn, 24) + 1)
        if nbn % sb == 0 and fits_budget(nbn, nbi, feed, sb)
    ]
    return tuple(sorted({1, _superblock(nbn), *divs}))


def fused_emittable(nbn: int, nbi: int, feed: str, sb: int) -> bool:
    """VMEM gate for one FUSED launch group: may the kernel run at the
    group's width (``nbi`` = widest member bucket) and super-block
    ``sb``?  ``emittable_superblocks`` admits the static fallback and
    sb = 1 WITHOUT the budget check (legacy escape hatches for configs
    the chooser never sees), so the fusion planner re-checks the chosen
    width explicitly — a fused group must never widen its members into
    a config the VMEM model rejects.  pp = 2 is the worst case the
    dispatch can pick (even chunk)."""
    from ..analysis.vmem import fits_budget

    return fits_budget(nbn, nbi, feed, sb, pp=2)


@functools.lru_cache(maxsize=256)
def _choose_superblock_cached(
    nbn: int, nbi: int, len1: int, lens_hist: tuple, feed: str = "i8"
) -> int:
    base, per_sb, rate = _SB_CONSTANTS[feed]
    kw = dict(base=base, per_sb=per_sb, rate=rate)
    best_sb, best_cost = None, None
    # Every divisor of nbn in [2, 24] passing the VMEM feasibility gate,
    # widest first (ties go wide).  The r3 bound extension 16 -> 24 lets
    # tiny-Seq2 batches against the caps-size Seq1 run ONE 24-block
    # super-block instead of two (interleaved A/B on input4: sb=24 beats
    # sb=12 in both passes, median +45%); the cost model keeps sb=12 for
    # max-size-class batches, whose dead-lane waste at sb=24 outweighs
    # the halved iteration count.  For 2 <= nbn <= 24 the divisors
    # always include nbn itself, which also covers the prime Seq1
    # buckets (13, 17, 19, 23); a larger prime nbn (huge ring shard)
    # must not allocate an nbn-wide band and falls back to the static
    # policy.
    candidates = [
        sb for sb in sorted(emittable_superblocks(nbn, nbi, feed))[::-1]
        if sb >= 2
    ]
    for sb in candidates:
        cost = superblock_model_cost(nbn, nbi, len1, lens_hist, sb, **kw)
        if best_cost is None or cost < best_cost:
            best_sb, best_cost = sb, cost
    return best_sb if best_sb is not None else _superblock(nbn)


def _packed_tile_superblocks(
    lens2, nbn: int, sb: int, len1: int, l2s: int
) -> int:
    """Total executed super-blocks across the row-packed tiles: pairs
    pack p = 128/l2s at a time IN ORDER, and each tile's block-skip gate
    uses the tile's live minimum length (matching `_kernel_packed`)."""
    p = _BLK // l2s
    lens_list = [int(x) for x in lens2]
    total = 0
    for t0 in range(0, len(lens_list), p):
        seg = [x for x in lens_list[t0 : t0 + p] if x > 0]
        # An all-padding tile still executes super-block 0 (the kernel
        # runs nb == 0 unconditionally; its l2min gate only skips later
        # blocks) — count it, or chunk-padded batches under-report
        # (accounting lockstep: callers pass the PADDED per-chunk lens).
        total += _live_superblocks(nbn, sb, len1, min(seg)) if seg else 1
    return total


def kernel_mxu_flops(
    len1: int, lens2, l1p: int, l2p: int, feed: str, sb: int | None = None,
    l2s: int | None = None,
) -> int:
    """MXU FLOPs (2 x MACs) the fused kernel ISSUES for one batch — the
    accounting for bench.py's true-MFU line (VERDICT r1 §1).

    Mirrors `_kernel`'s control flow exactly: per pair, super-block 0
    always runs, later super-blocks only while n0 < len1 - len2, and each
    executed super-block runs EXACTLY ``nbi_live`` char-block tiles (the
    r3 'tail1' walk: 2-wide even part + a 1-wide tail for odd counts —
    no rounded-up overhang tiles on any feed), each tile one one-hot
    matmul ([128, 128] @ [128, sbw + 128]) plus the prefix matmuls (two
    on the narrow feeds, one fused on f32).  ``l2s`` switches to the
    row-packed walk (`_kernel_packed`): p pairs per tile, one one-hot
    and ONE full-W block-diagonal prefix matmul per executed tile.
    Update in lockstep with any kernel reformulation, or the MFU line
    silently lies.
    """
    nbn, nbi = l1p // _BLK, l2p // _BLK
    sb = _superblock(nbn) if sb is None else sb
    sbw = sb * _BLK
    if l2s is not None:
        per_tile = 2 * _BLK * _BLK * (sbw + _BLK)  # one-hot + prefix, full W
        return 2 * per_tile * _packed_tile_superblocks(
            lens2, nbn, sb, len1, l2s
        )
    prefix_matmuls = 1 if feed == "f32" else 2
    per_tile = _BLK * _BLK * (sbw + _BLK) + prefix_matmuls * _BLK * _BLK * sbw
    total = 0
    for l2 in lens2:
        l2 = int(l2)
        # r3 tail1: the walk issues EXACTLY nbi_live tiles (even part
        # 2-wide + a 1-wide tail for odd counts) — no rounded-up overhang
        # tiles on any feed.
        tiles = min(-(-l2 // _BLK), nbi)  # 0 tiles for an empty pair
        total += _live_superblocks(nbn, sb, len1, l2) * tiles * per_tile
    return 2 * total


def kernel_vpu_pass_elems(
    len1: int, lens2, l1p: int, l2p: int, feed: str, sb: int | None = None,
    l2s: int | None = None,
) -> dict:
    """Full-width VPU-pass element counts per stage class for one batch
    call — the numerator of bench.py's VPU-floor accounting (VERDICT r3
    item 2: "bytes per full-width pass per tile for each stage").

    Mirrors `_kernel`'s walk exactly like :func:`kernel_mxu_flops` does
    (same live-super-block and tile counts); per executed tile the VPU
    touches:

    - ``rotate``: one strided rotate over the [128, sbw+128] accumulator
      (the shear; 32-bit, the only legal Mosaic formulation).
    - ``cast``: one narrowing int32->int8 pass over the same accumulator
      (narrow feeds only; the f32 feed's delta subtract is counted in
      the fma class instead).
    - ``fma``: the elementwise/reduction remainder at roughly fma-class
      cost per element — one-hot build (compare + cast on [128, 128]),
      the lp = pa - pb subtract, the pack-fma, and the row-max
      reduction, each one pass over [128, sbw].

    Epilogue/carry work on [1, sbw] / [sbw] vectors is ~1/128 of a tile
    pass and is not counted on the UNPACKED walk; the packed walk
    (``l2s`` set, mirroring `_kernel_packed`) runs p per-segment [1, W]
    epilogues per tile, which at p = 16 exceed a full-width pass and ARE
    counted (~10 thin passes per segment).  Update in lockstep with any
    kernel reformulation, or the floor silently lies.
    """
    nbn, nbi = l1p // _BLK, l2p // _BLK
    sb = _superblock(nbn) if sb is None else sb
    sbw = sb * _BLK
    if l2s is not None:
        p = _BLK // l2s
        W = sbw + _BLK
        per_tile = {
            # the shear + the cyclic rollP lane shift
            "rotate": 2 * W * _BLK,
            # i8: int32->int8 vb narrowing; bf16: f32->bf16 narrowing
            # PLUS the f32->int32 prefix cast; f32: prefix cast only
            # (vb re-cast is a no-op).
            "cast": (2 if feed == "bf16" else 1) * W * _BLK,
            # one-hot build + g subtract + gpack + segmented row-max
            # + p thin per-segment epilogues
            "fma": 2 * _BLK * _BLK + 3 * W * _BLK + 10 * p * W,
        }
        tiles = _packed_tile_superblocks(lens2, nbn, sb, len1, l2s)
        return {k: v * tiles for k, v in per_tile.items()}
    per_tile = {
        "rotate": (sbw + _BLK) * _BLK,
        "cast": (sbw + _BLK) * _BLK if feed != "f32" else 0,
        "fma": 2 * _BLK * _BLK + 3 * sbw * _BLK,
    }
    tiles = 0
    for l2 in lens2:
        l2 = int(l2)
        t = min(-(-l2 // _BLK), nbi)
        tiles += _live_superblocks(nbn, sb, len1, l2) * t
    return {k: v * tiles for k, v in per_tile.items()}


def _kernel(
    meta_ref, codes_ref, a_ref, out_ref, *, nbn, nbi, feed, pretiled, sb,
    pp, wide1=False,
):
    """One grid cell scores ``pp`` pairs (amortising the per-cell grid
    overhead), each across all offset super-blocks, reducing every pair to
    one best candidate: out lanes [score, n, k, eq] (f32; eq = the
    positional k=0 score at offset 0, for the equal-length path and the
    ring combine).

    Launch fusion rides this kernel unchanged: the scalar-prefetched
    ``meta_ref`` lens plane IS the per-cell bucket metadata — a fused
    launch concatenates several length buckets' rows padded to the
    group's L2P, and each pair's prefetched ``l2`` drives the
    ``nbi_live`` truncation and the super-block skip, so lanes past a
    member bucket's own width cost nothing and score nothing (the value
    table's zeroed code-0 row/column self-masks the padding)."""
    for pj in range(pp):
        _pair(
            meta_ref, codes_ref, a_ref, out_ref, pj,
            nbn=nbn, nbi=nbi, feed=feed, pretiled=pretiled, sb=sb, pp=pp,
            wide1=wide1,
        )


def _pair(
    meta_ref, codes_ref, a_ref, out_ref, pj, *, nbn, nbi, feed, pretiled,
    sb, pp, wide1=False,
):
    """Score pair slot ``pj`` of the current grid cell.  The derived
    dtypes and iota/ltri constants are rebuilt per call — they are pure
    functions of the static params, and Mosaic CSEs them across the
    unrolled pair copies."""
    len1 = meta_ref[0]  # scalar-prefetch SMEM array: [len1, lens...]
    l2 = meta_ref[1 + pl.program_id(0) * pp + pj]
    # First (one-hot) matmul operand type; a_ref arrives pre-cast.
    oh_t = _FEED_DTYPES[feed]
    # Prefix-matmul operand type: int8 on the i8 feed (|v| <= 127 slices of
    # an int32 V, ltri is 0/1 — int8 x int8 with int32 accumulation is
    # exact and runs at twice the bf16 MXU rate), bf16 on the bf16 feed
    # (integers |v| <= 128 are bf16-exact), f32 otherwise.
    dd_t = {"i8": jnp.int8, "bf16": jnp.bfloat16, "f32": jnp.float32}[feed]
    # Scoring pipeline dtype: the i8 feed stays integer end to end (prefix
    # sums, carries and the running max are int32 — exact by construction);
    # the wider feeds keep the float32 pipeline.
    sc_t = jnp.int32 if feed == "i8" else jnp.float32
    neg = -(1 << 30) if feed == "i8" else _NEG
    # Packed running argmax (i8 feed): one int32 carries (score, kappa) as
    # g * 4096 + (4095 - kappa), so the per-tile argmax is a single max
    # reduction instead of max + broadcast-compare + masked min-index
    # (ablation: the reduction stack is ~17% of kernel wall).  Larger g
    # wins; equal g -> smaller kappa wins (kappa grows monotonically over
    # tiles, so this is exactly the first-hit tie-break).  Exact while
    # |g| <= l2p * 254 and kappa <= l2p fit: |pack| <= 520192 * 4096 +
    # 4095 < 2^31 for l2p <= 2048 — the BUF_SIZE_SEQ2 bucket ceiling;
    # wider (ring long-context) buckets keep the unpacked path.
    packed = feed == "i8" and nbi * _BLK <= PACKED_L2P_CEILING
    _KB = PACK_RADIX
    sbw = sb * _BLK  # offset lanes per super-block

    ri1 = lax.broadcasted_iota(jnp.int32, (_BLK, _BLK), 0)
    ci1 = lax.broadcasted_iota(jnp.int32, (_BLK, _BLK), 1)
    riw = lax.broadcasted_iota(jnp.int32, (_BLK, sbw), 0)
    liw = lax.broadcasted_iota(jnp.int32, (1, sbw), 1)
    ltri = (ri1 >= ci1).astype(dd_t)

    # Char-blocks wholly past len2 contribute nothing (the self-masking
    # table makes their deltas exactly zero): the dynamic trip count skips
    # them entirely.
    nbi_live = jnp.minimum((l2 + _BLK - 1) // _BLK, nbi)

    # Tiles per loop iteration.  Stage-major interleaving of two
    # independent tiles (all one-hot matmuls issued, then all rotates,
    # then all prefix matmuls, then the reductions) lets the hardware
    # overlap MXU matmuls with VPU rotates/reductions — the stages are
    # cost-ADDITIVE in the 1-wide loop (measured by scripts/kernel_ablate:
    # pair2 ~10% faster; 4-wide regresses on VMEM pressure).  r6: the f32
    # feed now takes the 2-wide walk too — the old "double-width f32
    # tiles spill" parenthetical was an unmeasured assumption, and the
    # gated interleaved A/B (scripts/f32_bench.py F32_AB=wide) reads
    # 2-wide at +9.8% (input3-class), +6.4% (max-size, sb=12) and +4.1%
    # (skew, sb=2) with NO spill through sb=12 (two [128, 1664] f32
    # accumulators are ~1.7 MiB — well under the per-core VMEM budget;
    # 4-wide f32 does exceed it at sb >= 8 and stays rejected).  Only
    # nbi == 1 (tiny-Seq2 buckets) keeps wide=1: there the second tile
    # is ALWAYS the zeroed overhang, so wide=2 doubles every stage for
    # nothing — interleaved A/B on input4 (sb=24): wide=1 +33% median.
    wide = 1 if nbi == 1 or (feed == "f32" and wide1) else 2
    # The carryfold stage-4 form only lowers at wide=2: at wide=1 Mosaic
    # hits "Not implemented: Sublane broadcast" in the folded reduction
    # (same limitation as the f32 branch), so wide=1 keeps the pre-fold
    # full-width g pass.
    fold = packed and wide == 2

    for nb in range(0, nbn, sb):
        n0 = nb * _BLK
        slot0 = (nb // sb) * nbi  # static base into the pre-tiled A bands

        def ibody_gen(ibw, car, w, fold, slot0=slot0, n0=n0):
            carry, runmax, runkap, t1 = car
            acc_t = jnp.int32 if feed == "i8" else jnp.float32
            # TPU MXUs multiply f32 at bf16 precision by default; the f32
            # feed (128 < |v| <= max_exact_value(l2p) <= 32767) needs
            # multi-pass HIGHEST to stay exact (one operand is 0/1,
            # values fit 16 mantissa bits: 2*maxv <= 2^16 - 1 by the
            # HIGHEST-operand half of the bound).
            # The i8/bf16 feeds are exact natively.
            prec = lax.Precision.HIGHEST if feed == "f32" else None

            # -- stage 1: one-hot matmuls (MXU) --------------------------
            i0s, vps = [], []
            for half in range(w):
                raw = ibw * w + half if w > 1 else ibw
                if w > 1:
                    # With the r3 exact even-trip + 1-wide-tail walk
                    # (`nbody` below), raw never exceeds nbi_live - 1, so
                    # the clamp and the zeroing mask are belt-and-braces
                    # (they used to realise zeroed overhang tiles; see
                    # BASELINE.md r3 'tail1').  If a rounded-up trip
                    # count ever returns, note the ADVICE-r2 invariant:
                    # an overhang tile duplicates the running carry at
                    # kappas SMALLER than its true position, and the
                    # output stays correct only because the duplicate
                    # equals endg, which the epilogue's endg == runmax ->
                    # k=0 rule outranks.
                    ib = jnp.minimum(raw, nbi - 1)
                    ohb = (codes_ref[pj, ib, :, :] == ci1) & (raw < nbi)
                else:
                    ib = raw
                    ohb = codes_ref[pj, ib, :, :] == ci1
                i0 = ib * _BLK
                i0s.append(i0)
                if pretiled:
                    # A arrives pre-tiled per (super-block, char-block): a
                    # dynamic LEADING-axis index is address arithmetic on
                    # sublane tiles, where a dynamic-start LANE slice of a
                    # flat [128, Wneed] A costs a cross-lane shift copy of
                    # the whole band per tile (~0.5 us — the dominant
                    # per-iteration overhead in the sb sweep).  Bands are
                    # stored lane-reversed: slot (nb//sb)*nbi + ib covers
                    # original columns [n0+i0, n0+i0+sbw+128) descending.
                    aband = a_ref[slot0 + ib, :, :]
                else:
                    # Flat [128, Wneed] band: the overlapping pre-tiled
                    # layout would exceed the VMEM budget (f32 feed at the
                    # size caps, ring long-context shards) — pay the
                    # dynamic lane-slice copy instead.
                    astart = pl.multiple_of(
                        a_ref.shape[1] - (n0 + i0) - (sbw + _BLK), _BLK
                    )
                    aband = a_ref[:, pl.ds(astart, sbw + _BLK)]
                # No explicit pad mask: row/col 0 of the value table are
                # zeroed host-side (code 0 appears only as padding), so
                # padded seq2 chars and seq1 positions past len1
                # contribute exactly 0 through the matmul itself.
                vps.append(
                    jnp.dot(
                        ohb.astype(oh_t),
                        aband,
                        preferred_element_type=acc_t,
                        precision=prec,
                    )
                )

            # -- stage 2: shear (VPU) ------------------------------------
            # Shear row r left by r = strided rotate right by r on the
            # reversed lanes; one hardware op replaces the 7-step
            # roll+select ladder.  Rows use only lanes j >= r, so the
            # rotate's wraparound never contaminates a consumed lane.
            # (Mosaic only rotates 32-bit data, so the shear runs on the
            # accumulator and any narrowing cast follows it.)
            vps = [
                pltpu.roll(vp, shift=0, axis=1, stride=1, stride_axis=0)
                for vp in vps
            ]
            # Reversed-lane diagonals: lane m holds offset n0+sbw-1-m.

            # -- stage 3: prefix matmuls (MXU) ---------------------------
            lps, t1incs = [], []
            for vp in vps:
                if feed == "f32":
                    # f32 MXU runs at ~1/8 the bf16 rate: one fused matmul
                    # on the delta, t1 via a VPU sublane reduction.
                    d0 = vp[:, _BLK:]
                    d1 = vp[:, _BLK - 1 : sbw + _BLK - 1]
                    dd = (d0 - d1).astype(dd_t)
                    lps.append(
                        jnp.dot(
                            ltri,
                            dd,
                            preferred_element_type=jnp.float32,
                            # |dd| <= 2*maxv <= 2^16 - 1 > bf16-exact
                            precision=lax.Precision.HIGHEST,
                        )
                    )
                    t1incs.append(jnp.sum(d1, axis=0))
                else:
                    # Split prefix matmuls: lp = ltri@d0 - ltri@d1, and
                    # row 127 of ltri@d1 (the all-ones row) IS sum(d1) —
                    # this tile's t1 increment.  The second cheap narrow
                    # matmul replaces two full-tile VPU passes (the dd
                    # subtract and the t1 sublane reduction), worth ~1.35x
                    # on the i8 feed (BASELINE.md).  On the i8 feed the
                    # matmuls run int8 x int8 -> int32 (exact, twice the
                    # bf16 rate); bf16 likewise (integers |v| <= 128 are
                    # bf16-exact).
                    vb = vp.astype(dd_t)
                    pa = jnp.dot(
                        ltri, vb[:, _BLK:], preferred_element_type=sc_t
                    )
                    pb = jnp.dot(
                        ltri,
                        vb[:, _BLK - 1 : sbw + _BLK - 1],
                        preferred_element_type=sc_t,
                    )
                    lps.append(pa - pb)
                    t1incs.append(pb[_BLK - 1, :])

            # -- stage 4: streaming reductions (VPU) ---------------------
            # The carry is constant across rows, so it COMMUTES with the
            # row-max: reduce the TILE-LOCAL prefix surface first, inject
            # the carry on the reduced [sbw] lane vector after (r3
            # ablation 'carryfold': one fewer full-width pass per tile on
            # a VPU-bound kernel; pooled interleaved A/Bs read ~+2.5%,
            # within the shared-chip noise band — kept on the pass-count
            # argument).
            # No kappa-validity mask: rows past len2 have zero deltas
            # (the self-masking table), so their row DUPLICATES the last
            # valid row's value — the max is unchanged, and the
            # smaller-kappa tie-break (min-index / packed low bits)
            # picks the real row.
            for i0, lp, t1i in zip(i0s, lps, t1incs):
                t1 = t1 + t1i
                if fold:
                    # kappa = i0 + riw + 1: 4095 - kappa = (4094-i0) - riw.
                    # (lp + carry)*KB + kb == lp*KB + kb + carry*KB: the
                    # carry term joins after the reduction.  |lp| <=
                    # 128*127 so |tp| < 2^27; adding |carry*KB| <=
                    # 2048*127*4096 keeps the total < 2^31 (the same
                    # bound as the pre-fold packing).
                    tp = lp * _KB + ((_KB - 2 - i0) - riw)
                    runmax = jnp.maximum(
                        runmax, jnp.max(tp, axis=0) + carry * _KB
                    )
                elif packed:
                    # wide=1 packed path: pre-fold form (see `fold`).
                    g = lp + carry[None, :]
                    gpack = g * _KB + ((_KB - 2 - i0) - riw)
                    runmax = jnp.maximum(runmax, jnp.max(gpack, axis=0))
                else:
                    # No carry fold here: folding (bmax = max(lp) + carry)
                    # trips "Not implemented: Sublane broadcast" in the
                    # select_n below on the f32 wide=1 lowering (r3,
                    # measured on-device); this branch only serves the
                    # non-critical f32/bf16/wide-bucket feeds, so it keeps
                    # the full-width g pass.
                    g = lp + carry[None, :]
                    bmax = jnp.max(g, axis=0)  # [sbw]
                    brow = jnp.min(
                        jnp.where(g == bmax[None, :], riw, _BIGROW), axis=0
                    )
                    upd = bmax > runmax
                    runmax = jnp.where(upd, bmax, runmax)
                    runkap = jnp.where(upd, i0 + brow + 1, runkap)
                carry = carry + lp[_BLK - 1, :]
            return carry, runmax, runkap, t1

        ibody = functools.partial(ibody_gen, w=wide, fold=fold)

        zeros = jnp.zeros((sbw,), sc_t)
        init = (
            zeros,
            jnp.full((sbw,), INT32_PACKED_SENTINEL if packed else neg, sc_t),
            jnp.zeros((sbw,), jnp.int32),
            zeros,
        )

        def nbody():
            if wide == 1:
                return lax.fori_loop(0, nbi_live, ibody, init)
            # r3 'tail1': exact even trip count, then ONE 1-wide tail
            # iteration when nbi_live is odd — the former rounded-up trip
            # ran a full zeroed-overhang tile pipeline for every
            # odd-nbi_live pair (interleaved A/Bs on input3: +5.6%
            # median; tail1's walls are also markedly more stable).  The
            # tail uses the pre-fold stage-4 (the carryfold reduction
            # does not lower at 1-wide — Mosaic "Sublane broadcast").
            car = lax.fori_loop(0, nbi_live // 2, ibody, init)
            return lax.cond(
                nbi_live % 2 == 1,
                lambda c: ibody_gen(nbi_live - 1, c, w=1, fold=False),
                lambda c: c,
                car,
            )

        if nb == 0:
            # Always runs: carries the equal-length k=0 capture at n=0.
            carry, runmax, runkap, t1 = nbody()
        else:
            # Super-blocks wholly past the pair's valid range
            # (n >= len1 - len2) are dead lanes (masked below): skip.
            carry, runmax, runkap, t1 = lax.cond(
                n0 < len1 - l2, nbody, lambda: init
            )

        # Zero deltas past len2 also mean the final prefix carry IS
        # G[len2] — the k=0 candidate — with no separate capture pass.
        endg = carry
        if packed:
            # Decode (score, kappa) from the packed running max; // and &
            # have floor / two's-complement semantics, so negative scores
            # decode exactly.
            runkap = (_KB - 1) - (runmax & (_KB - 1))
            runmax = runmax // _KB

        # -- in-kernel per-super-block argmax over offsets ----------------
        # The round-1 design wrote three [B, W] reversed surfaces and left
        # masking, un-reversing and the offset argmax to an XLA epilogue;
        # measured on-device that epilogue cost ~33 us/call (~17%) — more
        # than either matmul stage — almost all of it the un-reverse.
        # Reducing to one best candidate per pair here makes the kernel
        # output O(1) and the epilogue trivial.
        # All quantities stay [1, 1] VECTORS (keepdims reductions): each
        # vector->scalar extraction is a scalar-unit round trip that
        # stalls the vector pipeline, and there are four per super-block.
        kvec = jnp.where(endg == runmax, 0, runkap)  # k=0 wins ties
        # Reversed lanes: lane m holds global offset n = n0 + sbw-1-m.
        nvec = (n0 + sbw - 1) - liw
        if packed:
            # r3 'epipack': (score, lane) in ONE int32 so the masked best
            # and the first-hit lane come from a single max reduction
            # (equal scores pick the larger lane = the smaller offset =
            # first hit; the unpacked path needs max + broadcast-compare
            # + second max).  Lane field = pow2 >= sbw (<= 4096 at the
            # sb <= 24 grid bound); |score| <= 2048*127 on the packed
            # feed, so |pack| <= 260096*4096 + 4095 < 2^31.  Negative
            # packs decode exactly: >> is arithmetic (floor) and the low
            # bits hold liw verbatim in two's complement.
            klb = max((sbw - 1).bit_length(), 1)
            sv = t1 + runmax  # int32 [sbw]
            spack = jnp.where(
                nvec < len1 - l2,
                sv[None, :] * (1 << klb) + liw,
                jnp.int32(INT32_PACKED_SENTINEL),
            )
            best = jnp.max(spack, axis=1, keepdims=True)  # [1, 1]
            mstar = best & ((1 << klb) - 1)
            # All-invalid super-block (every lane masked): decode to the
            # same _NEG sentinel the unpacked path carries, instead of
            # leaking the decoded pack sentinel -(2^31-1) >> klb (~-5e5)
            # as a plausible int32 score — the ring combine's all-invalid
            # guard tests against _NEG (ADVICE r3).
            sbbest = jnp.where(
                best == jnp.int32(INT32_PACKED_SENTINEL),
                jnp.float32(_NEG),
                (best >> klb).astype(jnp.float32),
            )
        else:
            svec = (t1 + runmax).astype(jnp.float32)
            sm = jnp.where(
                nvec < len1 - l2, svec[None, :], _NEG
            )  # [1, sbw]
            sbbest = jnp.max(sm, axis=1, keepdims=True)  # [1, 1]
            # First-hit tie-break = smallest n = LARGEST reversed lane.
            mstar = jnp.max(
                jnp.where(sm == sbbest, liw, -1), axis=1, keepdims=True
            )
        nstar = (n0 + sbw - 1) - mstar
        kstar = jnp.sum(
            jnp.where(liw == mstar, kvec[None, :], 0), axis=1, keepdims=True
        )
        if nb == 0:
            bscore, bn, bk = sbbest, nstar, kstar
            # Equal-length capture: global n=0 is reversed lane sbw-1.
            eqv = jnp.sum(
                jnp.where(
                    liw == sbw - 1,
                    (t1 + endg).astype(jnp.float32)[None, :],
                    0.0,
                ),
                axis=1,
                keepdims=True,
            )
        else:
            # Strictly-greater keeps the earlier (smaller-n) super-block.
            upd = sbbest > bscore
            bscore = jnp.where(upd, sbbest, bscore)
            bn = jnp.where(upd, nstar, bn)
            bk = jnp.where(upd, kstar, bk)

    lo = lax.broadcasted_iota(jnp.int32, (1, _BLK), 1)
    vec = jnp.where(
        lo == 0,
        bscore,
        jnp.where(
            lo == 1,
            bn.astype(jnp.float32),
            jnp.where(
                lo == 2,
                bk.astype(jnp.float32),
                jnp.where(lo == 3, eqv, 0.0),
            ),
        ),
    )
    out_ref[pj, :, :] = vec


# Pre-tiled A bands beyond this budget (f32 feed at the size caps, ring
# long-context shards) fall back to the flat layout + dynamic lane slice:
# the overlapping tiles multiply the footprint by ~bandw/128, and the whole
# array must stay VMEM-resident across the grid.
_PRETILE_BUDGET_BYTES = 8 << 20


def _pretile_ok(nbn: int, nbi: int, feed: str, sb: int) -> bool:
    slots = (nbn // sb) * nbi
    bandw = sb * _BLK + _BLK
    itemsize = 1 if feed == "i8" else 2 if feed == "bf16" else 4
    return slots * _BLK * bandw * itemsize <= _PRETILE_BUDGET_BYTES


@functools.lru_cache(maxsize=32)
def _pallas_call(
    nbn: int,
    nbi: int,
    wneed: int,
    b: int,
    interpret: bool,
    feed: str,
    sb: int,
    pp: int = 1,
    wide1: bool = False,
):
    pretiled = _pretile_ok(nbn, nbi, feed, sb)
    kernel = functools.partial(
        _kernel, nbn=nbn, nbi=nbi, feed=feed, pretiled=pretiled, sb=sb,
        pp=pp, wide1=wide1,
    )
    slots = (nbn // sb) * nbi
    bandw = sb * _BLK + _BLK
    a_spec = (
        pl.BlockSpec((slots, _BLK, bandw), lambda p, lens: (0, 0, 0))
        if pretiled
        else pl.BlockSpec((_BLK, wneed), lambda p, lens: (0, 0))
    )
    return pl.pallas_call(
        kernel,
        interpret=interpret,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,  # [1 + B] int32 [len1, lens...] in SMEM
            grid=(b // pp,),
            in_specs=[
                pl.BlockSpec(
                    (pp, nbi, _BLK, 1), lambda p, lens: (p, 0, 0, 0)
                ),
                a_spec,
            ],
            out_specs=[
                pl.BlockSpec((pp, 1, _BLK), lambda p, lens: (p, 0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, 1, _BLK), jnp.float32),
        ],
    )


def _pallas_best(
    seq1ext, len1, rows, lens, val_flat, feed="f32", sb=None, wide1=False
):
    """Run the fused kernel; returns per-pair best candidates
    ``(score, n, k, eq)``, each ``[B]`` (score/eq float32, n/k int32).

    ``score`` is the masked best over valid offsets n < len1 - len2 with
    the reference's first-hit tie-break (offset-major, k-ascending with
    k=0 first); all-invalid pairs carry the ``_NEG`` sentinel on every
    feed (the packed i8 epilogue maps its internal pack sentinel back to
    ``_NEG`` — ADVICE r3).  ``eq`` is
    the positional k=0 score at offset 0 (the equal-length fast path and
    the ring combine's device-0 capture).  Offset validity is the caller's
    ``len1`` view — the ring path passes a block-local effective len1, so
    ``n`` is block-local there.  ``sb`` is the offset-super-block width
    (choose_superblock at dispatch; None = the static policy)."""
    b, l2p = rows.shape
    w = seq1ext.shape[0] - l2p - 1  # == L1P (offset-axis extent)
    nbn, nbi = w // _BLK, l2p // _BLK
    wneed = w + l2p  # A columns reachable by n0 + i0 + sbw + 127
    sb = _superblock(nbn) if sb is None else sb

    a_t = _FEED_DTYPES[feed]
    val27 = val_flat.reshape(ALPHABET_SIZE, ALPHABET_SIZE).astype(jnp.float32)
    # Code 0 appears only as padding (real chars encode to 1..26): zeroing
    # its row/column makes padded positions self-masking inside the kernel's
    # matmul, so the kernel needs no per-tile pad select.
    val27 = val27.at[0, :].set(0.0).at[:, 0].set(0.0)
    oh1 = (
        seq1ext[:wneed, None].astype(jnp.int32)
        == jnp.arange(ALPHABET_SIZE, dtype=jnp.int32)[None, :]
    ).astype(jnp.float32)
    a_small = lax.dot_general(
        val27,
        oh1,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,  # f32-feed values exceed 2^8
    )  # [27, Wneed]; integer entries |v| <= 128 on the bf16 path: exact cast
    # Lane-reversed storage: the kernel's strided-rotate shear only turns
    # one way (see _kernel).
    a_ext = (
        jnp.zeros((_BLK, wneed), jnp.float32)
        .at[:ALPHABET_SIZE]
        .set(a_small[:, ::-1])
    ).astype(a_t)
    # Pre-tile the band per (super-block, char-block) slot when it fits
    # the VMEM budget: the kernel indexes bands by their LEADING axis
    # (cheap sublane addressing); a dynamic-start lane slice of the flat
    # array costs a cross-lane shift copy of the whole band per tile.
    # Slices overlap, so A3 is ~bandw/128 times the flat array.
    if _pretile_ok(nbn, nbi, feed, sb):
        sbw = sb * _BLK
        bandw = sbw + _BLK
        a_in = jnp.stack(
            [
                lax.slice_in_dim(
                    a_ext, wneed - (n0 + ib * _BLK) - bandw,
                    wneed - (n0 + ib * _BLK), axis=1
                )
                for n0 in range(0, nbn * _BLK, sbw)
                for ib in range(nbi)
            ]
        )
    else:
        a_in = a_ext

    codes = rows.astype(jnp.int32).reshape(b, nbi, _BLK, 1)
    meta = jnp.concatenate(
        [jnp.reshape(len1, (1,)).astype(jnp.int32), lens.astype(jnp.int32)]
    )

    # Off-TPU (the 8-virtual-device CPU test mesh) the Mosaic kernel cannot
    # lower; interpret mode runs the same kernel semantics for parity tests.
    interpret = jax.default_backend() != "tpu"
    # Two pairs per grid cell amortise the per-cell overhead (DMA setup,
    # prologue) when the batch divides evenly.  An r3 sequential A/B
    # suggested pp=1 paid +20% on the caps-size class, but an
    # INTERLEAVED re-run showed ±0.5% — the delta was co-tenant drift
    # between measurements, not the kernel; pp=2 stands on the only
    # other datum (the r3 sequential matrix read pp=1 as -5.3% on
    # input3, same caveat about sequential A/Bs).
    pp = 2 if b % 2 == 0 else 1
    out = _pallas_call(nbn, nbi, wneed, b, interpret, feed, sb, pp, wide1)(
        meta, codes, a_in
    )[0][:, 0, :]
    return (
        out[:, 0],
        out[:, 1].astype(jnp.int32),
        out[:, 2].astype(jnp.int32),
        out[:, 3],
    )


def _kernel_packed(
    meta_ref, codes_ref, a_ref, out_ref, *, nbn, pretiled, sb, l2s, feed
):
    """Row-packed grid cell: p = 128/l2s pairs share ONE [128, W] tile
    (VERDICT r3 item 3 — tiny-Seq2 batches wasted rows 82..127 of every
    tile; the full-width stage passes now amortise over p pairs).

    The affine strided rotate gives segment j (rows [j*l2s, (j+1)*l2s))
    an extra uniform rotation of j*l2s, so its diagonals land CYCLICALLY
    shifted in the lane axis; with a block-diagonal ltri and the prefix
    matmul run over the FULL W = sbw+128 lanes, every (segment, offset,
    kappa) cell inside the per-block window [n0, n0+sbw) is exact —
    including the wrapped low lanes — because the rotate is cyclic over
    in-band data (validated cell-by-cell in scripts/rowpack_proto.py;
    the d1 seam only appears at offsets >= n0+sbw+128-l2s, outside the
    window).  One full-W prefix matmul replaces the unpacked pa/pb pair:
    prefix commutes with the lane shift, so pb = roll(P, 1 lane) and
    lp = P - roll(P).  The per-lane argmax packs an offset-ORDER key
    (sbw-1 - (n-n0)) instead of the raw lane index: segment j's lanes
    are cyclically permuted, so the lane index no longer orders offsets
    and the first-hit tie-break would break without it.

    All three feeds pack (r6; dispatch-gated by ``pack_classes``): the
    matmuls run in the feed dtype (f32 accumulate; HIGHEST for the f32
    feed, whose operands exceed bf16 exactness), and the prefix result
    is cast to int32 BEFORE the pack arithmetic, so the argmax-key
    packing is integer-exact whenever ``3 * l2s * maxv < 2**19``:
    |g| <= l2s*maxv and |sv| <= 2*l2s*maxv, and with klb <= 12 (sb <=
    24) and the kappa base _KB = 2^12 both ``gpack`` and ``spack`` stay
    inside int32.  i8 (maxv <= 127) passes every class by construction;
    bf16 (maxv <= 128) likewise; f32 classes shrink as maxv grows."""
    p = _BLK // l2s
    sbw = sb * _BLK
    W = sbw + _BLK
    _KB = PACK_RADIX
    klb = max((sbw - 1).bit_length(), 1)
    neg32 = jnp.int32(INT32_PACKED_SENTINEL)
    len1 = meta_ref[0]
    l2 = [meta_ref[1 + pl.program_id(0) * p + j] for j in range(p)]
    # Block-skip gate: a later super-block is dead when n0 >= len1 - l2
    # for EVERY live segment; padded segments (l2 = 0) must not hold
    # blocks alive, so they map to a huge length.
    big = jnp.int32(1 << 20)
    l2min = functools.reduce(
        jnp.minimum, [jnp.where(x > 0, x, big) for x in l2]
    )

    feed_t = _FEED_DTYPES[feed]
    acc_t = jnp.int32 if feed == "i8" else jnp.float32
    prec = lax.Precision.HIGHEST if feed == "f32" else None
    ri1 = lax.broadcasted_iota(jnp.int32, (_BLK, _BLK), 0)
    ci1 = lax.broadcasted_iota(jnp.int32, (_BLK, _BLK), 1)
    liw = lax.broadcasted_iota(jnp.int32, (1, W), 1)
    # Block-diagonal ltri: prefix sums stay segment-local.
    ltri_bd = ((ri1 >= ci1) & (ri1 // l2s == ci1 // l2s)).astype(feed_t)
    # kappa bits use the row index WITHIN the segment.
    rloc = lax.broadcasted_iota(jnp.int32, (_BLK, W), 0) & (l2s - 1)
    ohb = codes_ref[0, 0, :, :] == ci1

    bscore = [None] * p
    bn = [None] * p
    bk = [None] * p
    eqv = [None] * p

    for nb in range(0, nbn, sb):
        n0 = nb * _BLK
        slot = nb // sb

        def cands(n0=n0, slot=slot):
            if pretiled:
                aband = a_ref[slot, :, :]
            else:
                astart = pl.multiple_of(a_ref.shape[1] - n0 - W, _BLK)
                aband = a_ref[:, pl.ds(astart, W)]
            vp = jnp.dot(
                ohb.astype(feed_t),
                aband,
                preferred_element_type=acc_t,
                precision=prec,
            )
            vp2 = pltpu.roll(vp, shift=0, axis=1, stride=1, stride_axis=0)
            vb = vp2.astype(feed_t)
            P = jnp.dot(
                ltri_bd, vb, preferred_element_type=acc_t, precision=prec
            )
            if feed != "i8":
                # Integer-exact under the 3*l2s*maxv < 2^19 dispatch
                # gate; everything downstream is the i8 int32 pack path.
                P = P.astype(jnp.int32)
            # prefix(d1) = prefix(d0) shifted one lane (cyclic): the band
            # is contiguous, so the cyclic neighbour IS position+1 inside
            # the window (rowpack_proto.py part 1).
            rollP = pltpu.roll(P, shift=1, axis=1)
            g = P - rollP
            gpack = g * _KB + ((_KB - 2) - rloc)
            out = []
            for j in range(p):
                rend = (j + 1) * l2s - 1
                seg = gpack[j * l2s : (j + 1) * l2s, :]
                rmax = jnp.max(seg, axis=0, keepdims=True)  # [1, W]
                kap = (_KB - 1) - (rmax & (_KB - 1))
                gdec = rmax // _KB
                endg = g[rend : rend + 1, :]
                t1v = rollP[rend : rend + 1, :]
                kvec = jnp.where(endg == gdec, 0, kap)  # k=0 wins ties
                # Segment j's cyclic lane -> offset map (static shift).
                tmp = (sbw + _BLK - 1 + j * l2s) - liw
                nrel = jnp.where(tmp >= W, tmp - W, tmp)  # n - n0
                # Offset-order key: bigger key = smaller n = first hit.
                key = (sbw - 1) - nrel
                sv = t1v + gdec
                valid = (nrel < sbw) & (n0 + nrel < len1 - l2[j])
                spack = jnp.where(valid, sv * (1 << klb) + key, neg32)
                best = jnp.max(spack, axis=1, keepdims=True)  # [1, 1]
                kstar_key = best & ((1 << klb) - 1)
                sj = jnp.where(
                    best == neg32,
                    jnp.float32(_NEG),
                    (best >> klb).astype(jnp.float32),
                )
                nj = n0 + (sbw - 1) - kstar_key
                # key is unique among valid lanes (lane->n is a cyclic
                # bijection), so this sum selects exactly the winner.
                kj = jnp.sum(
                    jnp.where(valid & (key == kstar_key), kvec, 0),
                    axis=1,
                    keepdims=True,
                )
                ej = jnp.sum(
                    jnp.where(
                        (nrel == 0) & (n0 == 0),
                        (t1v + endg).astype(jnp.float32),
                        0.0,
                    ),
                    axis=1,
                    keepdims=True,
                )
                out.extend([sj, nj.astype(jnp.float32), kj.astype(jnp.float32), ej])
            return tuple(out)

        if nb == 0:
            flat = cands()
        else:
            dead = tuple(
                jnp.full((1, 1), _NEG if i % 4 == 0 else 0.0, jnp.float32)
                for i in range(4 * p)
            )
            flat = lax.cond(n0 < len1 - l2min, cands, lambda: dead)
        for j in range(p):
            sj, nj, kj, ej = flat[4 * j : 4 * j + 4]
            if nb == 0:
                bscore[j], bn[j], bk[j], eqv[j] = sj, nj, kj, ej
            else:
                upd = sj > bscore[j]
                bscore[j] = jnp.where(upd, sj, bscore[j])
                bn[j] = jnp.where(upd, nj, bn[j])
                bk[j] = jnp.where(upd, kj, bk[j])

    lo = lax.broadcasted_iota(jnp.int32, (1, _BLK), 1)
    for j in range(p):
        vec = jnp.where(
            lo == 0,
            bscore[j],
            jnp.where(
                lo == 1,
                bn[j],
                jnp.where(lo == 2, bk[j], jnp.where(lo == 3, eqv[j], 0.0)),
            ),
        )
        out_ref[j, :, :] = vec


@functools.lru_cache(maxsize=32)
def _pallas_call_packed(
    nbn: int,
    wneed: int,
    tiles: int,
    interpret: bool,
    sb: int,
    l2s: int,
    feed: str = "i8",
):
    pretiled = _pretile_ok(nbn, 1, feed, sb)
    p = _BLK // l2s
    kernel = functools.partial(
        _kernel_packed, nbn=nbn, pretiled=pretiled, sb=sb, l2s=l2s, feed=feed
    )
    slots = nbn // sb
    bandw = sb * _BLK + _BLK
    a_spec = (
        pl.BlockSpec((slots, _BLK, bandw), lambda t, lens: (0, 0, 0))
        if pretiled
        else pl.BlockSpec((_BLK, wneed), lambda t, lens: (0, 0))
    )
    return pl.pallas_call(
        kernel,
        interpret=interpret,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,  # [1 + tiles*p] int32 [len1, lens...]
            grid=(tiles,),
            in_specs=[
                pl.BlockSpec((1, 1, _BLK, 1), lambda t, lens: (t, 0, 0, 0)),
                a_spec,
            ],
            out_specs=[
                pl.BlockSpec((p, 1, _BLK), lambda t, lens: (t, 0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((tiles * p, 1, _BLK), jnp.float32),
        ],
    )


def _pallas_best_packed(
    seq1ext, len1, rows, lens, val_flat, feed="i8", sb=None, l2s=64
):
    """Row-packed variant of :func:`_pallas_best` for nbi == 1 buckets
    whose every pair has len2 <= l2s (any feed whose packing class
    passes ``dispatch.pack_classes`` — the 3*l2s*maxv < 2^19 int32
    epilogue bound; enforced at dispatch).  Same return contract;
    p = 128/l2s pairs per tile."""
    b, l2p = rows.shape
    if l2p != _BLK:
        # Runtime path: must survive python -O (seqlint SEQ004).
        raise RuntimeError(
            f"row-packed kernel requires a single char-block bucket "
            f"(L2P == {_BLK}), got L2P={l2p}; dispatch.choose_rowpack "
            "must not emit l2s for wider buckets"
        )
    w = seq1ext.shape[0] - l2p - 1
    nbn = w // _BLK
    wneed = w + l2p
    sb = _superblock(nbn) if sb is None else sb
    p = _BLK // l2s
    tiles = -(-b // p)

    val27 = val_flat.reshape(ALPHABET_SIZE, ALPHABET_SIZE).astype(jnp.float32)
    val27 = val27.at[0, :].set(0.0).at[:, 0].set(0.0)
    oh1 = (
        seq1ext[:wneed, None].astype(jnp.int32)
        == jnp.arange(ALPHABET_SIZE, dtype=jnp.int32)[None, :]
    ).astype(jnp.float32)
    a_small = lax.dot_general(
        val27, oh1, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        # f32-feed weights exceed the default precision's bf16-exact
        # range; i8/bf16 values fit and keep the fast path.
        precision=lax.Precision.HIGHEST if feed == "f32" else None,
    )
    a_ext = (
        jnp.zeros((_BLK, wneed), jnp.float32)
        .at[:ALPHABET_SIZE]
        .set(a_small[:, ::-1])
    ).astype(_FEED_DTYPES[feed])
    if _pretile_ok(nbn, 1, feed, sb):
        sbw = sb * _BLK
        bandw = sbw + _BLK
        a_in = jnp.stack(
            [
                lax.slice_in_dim(
                    a_ext, wneed - n0 - bandw, wneed - n0, axis=1
                )
                for n0 in range(0, nbn * _BLK, sbw)
            ]
        )
    else:
        a_in = a_ext

    # Pack p pairs' first l2s code columns into each tile's 128 rows
    # (columns >= l2s are zero for every pair by the l2s bound).
    rows_p = jnp.zeros((tiles * p, l2s), rows.dtype).at[:b].set(rows[:, :l2s])
    codes = rows_p.astype(jnp.int32).reshape(tiles, 1, _BLK, 1)
    lens_p = jnp.zeros((tiles * p,), jnp.int32).at[:b].set(
        lens.astype(jnp.int32)
    )
    meta = jnp.concatenate(
        [jnp.reshape(len1, (1,)).astype(jnp.int32), lens_p]
    )

    interpret = jax.default_backend() != "tpu"
    out = _pallas_call_packed(nbn, wneed, tiles, interpret, sb, l2s, feed)(
        meta, codes, a_in
    )[0][:b, 0, :]
    return (
        out[:, 0],
        out[:, 1].astype(jnp.int32),
        out[:, 2].astype(jnp.int32),
        out[:, 3],
    )


def _pallas_rows(
    seq1ext, len1, rows, lens, val_flat, feed="f32", sb=None, l2s=None,
    wide1=False,
):
    """Score a [B, L2P] padded batch with the fused kernel; returns [B, 3].
    ``l2s`` (dispatch-gated: ``pack_classes(feed, maxv)`` non-empty,
    L2P == 128, all len2 <= l2s) routes to the row-packed kernel.
    ``wide1`` forces the 1-wide walk (f32 A/B benches only)."""
    if l2s is not None:
        best, bn, bk, eq = _pallas_best_packed(
            seq1ext, len1, rows, lens, val_flat, feed=feed, sb=sb, l2s=l2s
        )
    else:
        best, bn, bk, eq = _pallas_best(
            seq1ext, len1, rows, lens, val_flat, feed=feed, sb=sb,
            wide1=wide1,
        )

    # O(B)-scalar epilogue: equal-length / unsearchable selection (the
    # offset masking and argmax happen inside the kernel).
    searchable = (lens < len1) & (lens > 0)
    score_f = jnp.where(lens == len1, eq, best)
    score = jnp.where(
        searchable | (lens == len1),
        score_f.astype(jnp.int32),
        jnp.int32(INT32_MIN),
    )
    out_n = jnp.where(searchable, bn, 0)
    out_k = jnp.where(searchable, bk, 0)
    return jnp.stack([score, out_n, out_k], axis=1)


def _shapes_supported(l1p: int, l2p: int) -> bool:
    return l1p % _BLK == 0 and l2p % _BLK == 0


def score_chunks_pallas_body(
    seq1ext, len1, seq2_chunks, len2_chunks, val_flat, *, feed="f32", sb=None,
    l2s=None, wide1=False,
):
    """Chunked-batch entry, same contract as the XLA bodies:
    [NC, CB, L2P] -> [NC, CB, 3].  Falls back to the XLA matmul body for
    non-128-aligned shape buckets (tiny problems).  ``feed`` must come
    from ``mxu_feed(val_flat)`` on concrete weights (checked at dispatch
    sites; this body may be traced with abstract values).  ``l2s``
    routes to the row-packed kernel (dispatch-gated: packing class in
    ``pack_classes(feed, maxv)``, L2P == 128, every len2 <= l2s).
    ``wide1`` (static) forces the pre-r6 1-wide f32 walk — an offline
    A/B dimension (scripts/f32_bench.py), never set by dispatch."""
    nc, cb, l2p = seq2_chunks.shape
    l1p = seq1ext.shape[0] - l2p - 1
    if not _shapes_supported(l1p, l2p):
        from .matmul_scorer import score_chunks_mm_body

        # feed is static: only the f32 feed's values exceed the MXU's
        # default-precision exactness bound (matmul_scorer.mm_precision).
        return score_chunks_mm_body(
            seq1ext,
            len1,
            seq2_chunks,
            len2_chunks,
            val_flat,
            mm_precision=lax.Precision.HIGHEST if feed == "f32" else None,
        )
    out = _pallas_rows(
        seq1ext,
        len1,
        seq2_chunks.reshape(nc * cb, l2p),
        len2_chunks.reshape(nc * cb),
        val_flat,
        feed=feed,
        sb=sb,
        l2s=l2s,
        wide1=wide1,
    )
    return out.reshape(nc, cb, 3)


# donate_argnums per the DonationPlan (analysis/dataflow.py) — see
# ops/xla_scorer.py for the pin rationale; `make donation-audit`
# cross-checks this literal against the proof.
score_chunks_pallas = jax.jit(
    score_chunks_pallas_body,
    static_argnames=("feed", "sb", "l2s", "wide1"),
    donate_argnums=(0, 2),
)

warnings.filterwarnings("ignore", message="Some donated buffers were not usable")


@functools.lru_cache(maxsize=32)
def pallas_pair_scorer(l1p: int, l2p: int, feed: str = "f32", sb: int | None = None):
    """Per-shard callable for the shard_map path: (seq1ext, len1,
    rows [BL, L2P], lens [BL], val_flat) -> [BL, 3].  Cached by shape
    bucket so the shard_map jit cache stays hot."""

    def fn(seq1ext, len1, rows, lens, val_flat):
        if not _shapes_supported(l1p, l2p):
            from .matmul_scorer import score_chunks_mm_body

            bl = rows.shape[0]
            return score_chunks_mm_body(
                seq1ext,
                len1,
                rows.reshape(bl, 1, l2p).transpose(1, 0, 2),
                lens.reshape(1, bl),
                val_flat,
                mm_precision=lax.Precision.HIGHEST if feed == "f32" else None,
            ).reshape(bl, 3)
        return _pallas_rows(
            seq1ext, len1, rows, lens, val_flat, feed=feed, sb=sb
        )

    return fn
