"""Signed pair-value table: class matrix x weights (reference C10+C13 scoring).

The reference scores a candidate alignment as ``w1*n$ - w2*n% - w3*n# - w4*n␣``
(spec PDF p.2; cudaFunctions.cu:103,161-163) by counting signs in a histogram.
On TPU, counting then weighting is just a dot product — so we fold the weights
into the class matrix once per run, producing a [27, 27] int32 table ``VAL``
with ``VAL[a, b]`` = the signed score contribution of pairing character ``a``
(from Seq2) with character ``b`` (from Seq1).  Histogram + weighting then
dissolve into a single masked sum over the sequence axis.
"""

from __future__ import annotations

import numpy as np

from ..models.classmat import build_class_matrix
from ..utils.constants import NUM_WEIGHTS


def signed_weights(weights) -> np.ndarray:
    """[4] int32 vector of per-class signed contributions: [+w0, -w1, -w2, -w3]."""
    w = np.asarray(weights, dtype=np.int64).reshape(-1)
    if w.size != NUM_WEIGHTS:
        raise ValueError(f"expected {NUM_WEIGHTS} weights, got {w.size}")
    return np.array([w[0], -w[1], -w[2], -w[3]], dtype=np.int32)


def value_table(weights) -> np.ndarray:
    """[27, 27] int32 table of signed pair values for the given weights."""
    return signed_weights(weights)[build_class_matrix()]


def max_abs_value(val_flat) -> int:
    """Largest |entry| of a value table, for the float exactness gates.
    int64: abs(int32 min) would wrap negative and mis-enable a gate."""
    return int(np.abs(np.asarray(val_flat, dtype=np.int64)).max())
