"""The serve loop: warm caches, continuous batching, drain → 75.

One :class:`ServeLoop` owns the run: the admission queue, the pending
window, and the jit caches (held warm simply by the process living —
the scorer and its compiled shapes persist across requests, which is
the whole point of serving versus one-shot batch runs).

A **tick** is the unit of work: pop whatever coalesced in the gather
window, validate each raw request into a :class:`.session.Session`
(typed error record on failure — the loop outlives bad input), plan
the pooled rows into fixed-shape superblocks, dispatch every block
through the shared :class:`..io.pipeline.ChunkPipeline` (async, windowed,
prefetched), then flush and demux rows back to sessions by tag.  Every
dispatch rides the SAME retry/degrade/watchdog machinery as the batch
CLI — a deadline-expired superblock is retried, not wedged.

**Drain**: the PR-4 guard's SIGTERM flag is checked at tick boundaries
and inside the queue wait (bounded, via the injectable clock — worst
case one tick of latency).  On drain: admission closes, in-flight
superblocks finish and their lines stream out, queued-but-unstarted
requests are journaled (whole-file atomic serve journal) and notified
``{"drained": true}``, and :class:`DrainInterrupt` surfaces → the CLI's
exit 75.  ``--serve --journal P --resume`` re-admits the journaled
requests before reading any new input.

**Steady-state compiles**: the PR-3 recompile detector baselines after
the first block finishes; everything after must hit warm caches.  The
delta is exported as the ``serve_steady_compiles`` gauge —
``make serve-smoke`` gates on it being 0.  Under ``--prewarm`` the AOT
warm plane compiles the block shapes BEFORE the loop starts and
:meth:`ServeLoop.baseline_steady` pins the baseline at tick 0 — the
first block is no longer a grace period, and ``make aot-smoke`` gates
the stricter contract.

**SLO armor** (PR 9): admission is a cost-aware token bucket plus the
accept/shed-new/drain-only machine (:mod:`.slo`); per-request deadlines
are checked at admission pricing, at batch planning
(:meth:`ServeLoop._admit_sessions`), and at demux
(:meth:`.session.Session.fill`); a superblock that fails past its whole
retry/degrade ladder is retried once whole and then BISECTED so one
poison request is isolated with a typed error while its co-batched
victims re-plan onto clean blocks; and the pipeline's circuit breaker
(:mod:`..resilience.breaker`), ticked here, pins the degraded backend
after repeated primary failures.

**Fleet** (docs/ARCHITECTURE.md §8.6): with ``--fleet-board`` the loop
is the fleet COORDINATOR — planning, admission, SLO armor, and demux
are unchanged, but planned superblocks are offered to ``--fleet-worker``
processes through :class:`.fleet.FleetCoordinator` under expiring
leases, and the loop's tick pumps membership/lease/result collection.
With no live workers every block scores locally, exactly as before.

**Crash survival** (``kill:serve-tick`` chaos tier): while ``--journal``
is armed, the journal continuously holds every admitted-but-unanswered
raw request — queued AND in-flight — rewritten (whole-file atomic) at
tick boundaries whenever the set changes.  A SIGKILL mid-serve loses
nothing: the rerun's ``--resume`` re-admits exactly the unanswered
requests, and since a request leaves the journal only after its done
record went out, the rerun can never double-answer one.

Threading: socket reader threads only ``json.loads`` + enqueue (see
:mod:`.queue`); parsing, scoring, span recording, and ALL journal/metric
mutation happen on the main loop thread.
"""

from __future__ import annotations

import collections
import json
import os
import socket as socketlib
import struct
import sys
import threading

import numpy as np

from ..analysis.recompile import compile_count
from ..io.pipeline import FeedStager, PendingWindow
from ..obs.events import log_line, publish
from ..obs.metrics import gauge as obs_gauge
from ..obs.spans import span
from ..resilience.drain import DrainInterrupt, drain_requested
from ..resilience.faults import InjectedFatalFaultError
from ..resilience.faults import fire as _fault_fire
from ..resilience.faults import scheduled as _fault_scheduled
from ..utils.constants import BUF_SIZE_SEQ2
from ..utils.platform import env_float, env_int
from .batcher import DEFAULT_BLOCK_ROWS, SuperBlock, plan_blocks
from .clock import ServeClock
from .queue import ADMIT_CLOSED, ADMIT_FULL, ADMIT_OVERLOADED, RequestQueue
from .session import (
    RequestError,
    Responder,
    build_session,
    journal_drained,
    load_drained,
    parse_raw,
)
from .slo import SHED_DRAIN, AdmissionController

#: Upper bound on one queue wait: the drain flag is re-checked at least
#: this often even if no request ever arrives.
_TICK_S = 0.25


class ServeLoop:
    """The serving run's state: queue, window, pipeline, drain plumbing."""

    def __init__(
        self,
        pipeline,
        policy,
        *,
        clock=None,
        journal_path: str | None = None,
        max_depth: int | None = None,
        window_s: float | None = None,
        rows_per_block: int | None = None,
        max_pop: int | None = None,
    ):
        self.pipeline = pipeline
        self.policy = policy
        self.clock = clock or ServeClock()
        self.journal_path = journal_path
        self.window_s = (
            window_s
            if window_s is not None
            else env_float("SEQALIGN_SERVE_WINDOW_S", 0.05)
        )
        self.rows_per_block = (
            rows_per_block
            if rows_per_block is not None
            else env_int("SEQALIGN_SERVE_BLOCK_ROWS", DEFAULT_BLOCK_ROWS)
        )
        self.max_pop = (
            max_pop
            if max_pop is not None
            else env_int("SEQALIGN_SERVE_MAX_POP", 0)
        )
        self.controller = AdmissionController(
            budget_s=env_float("SEQALIGN_SERVE_COST_BUDGET_S", 4.0),
            shed_wait_s=env_float("SEQALIGN_SERVE_SHED_WAIT_S", 30.0),
        )
        self.queue = RequestQueue(
            max_depth
            if max_depth is not None
            else env_int("SEQALIGN_SERVE_MAX_QUEUE", 256),
            self.clock,
            controller=self.controller,
        )
        self.window = PendingWindow(
            max(1, env_int("TPU_SEQALIGN_STREAM_DEPTH", 4)), self._finish
        )
        # Feed overlap (r6): within a tick, block N+1's host->device
        # transfers are staged while block N computes (_dispatch's
        # ``nxt`` lookahead).  Advisory and single-use, like the stream
        # path — see io.pipeline.FeedStager.
        self.stager = FeedStager(getattr(pipeline, "degrader", None))
        # The pipeline's circuit breaker (None without --degrade): the
        # loop ticks it so open/half-open transitions stay deterministic.
        self.breaker = getattr(pipeline, "breaker", None)
        self._steady_base: int | None = None
        # Fleet coordinator (run_serve attaches one under --fleet-board).
        self.fleet = None
        # Live-journal state: (session, raw) for every in-flight request,
        # plus the last journal body written (skip no-op rewrites).
        self._inflight: list[tuple] = []
        self._journal_state: str | None = None
        # Answered reply ids (bounded: the deque evicts, the set mirrors
        # it for O(1) lookup).  As fleet leader these ride the board
        # checkpoint — the successor's idempotency set — and make
        # reconnect-and-redrive duplicates answerable without rescoring.
        self._answered: collections.deque = collections.deque(maxlen=4096)
        self._answered_set: set[str] = set()

    # -- ingest (reader threads and the main-thread stdin loop) -----------

    def ingest(self, line: str, responder) -> None:
        """One wire line → parse-to-dict → admission; error record on a
        line that is not a JSON object, backpressure/drain verdicts
        relayed to the client."""
        line = line.strip()
        if not line:
            return
        try:
            raw = parse_raw(line)
        except RequestError as e:
            publish(
                "serve.request.rejected",
                reason="malformed",
                depth=self.queue.depth(),
            )
            responder.send({"id": None, "error": str(e)})
            return
        cmd = raw.get("cmd")
        if cmd is not None:
            # Read-only telemetry verbs ({"cmd": "metrics"|"healthz"|
            # "trace"}) answer inline from the live plane — never queued,
            # never priced against the admission bucket.
            self._telemetry(str(cmd), responder)
            return
        rid = raw.get("id")
        if (
            self.fleet is not None
            and self.fleet.leader is not None
            and rid is not None
            and str(rid) in self._answered_set
        ):
            # Reconnect-and-redrive idempotency: this id was already
            # answered — by this leader, or (via the checkpoint's
            # answered set) by the dead one.  A typed duplicate notice
            # instead of a rescore; advisory here, authoritative at
            # takeover replay.  Anonymous requests (no id) cannot be
            # deduplicated across a failover — documented at-least-once.
            publish("serve.request.duplicate", id=str(rid))
            responder.send({"id": rid, "duplicate": True})
            return
        verdict = self.queue.submit(raw, responder)
        if verdict == ADMIT_FULL:
            responder.send(
                {
                    "id": raw.get("id"),
                    "error": f"queue full ({self.queue.max_depth} requests "
                    "queued); resubmit later",
                }
            )
        elif verdict == ADMIT_OVERLOADED:
            responder.send(
                {
                    "id": raw.get("id"),
                    "error": "overloaded",
                    "retry_after_s": self.controller.retry_after_s(),
                }
            )
        elif verdict == ADMIT_CLOSED:
            responder.send(
                {
                    "id": raw.get("id"),
                    "error": "server is draining; resubmit elsewhere",
                }
            )

    # -- telemetry (read-only, shared with the HTTP scrape) ----------------

    def status(self) -> dict:
        """Live health snapshot: the ``healthz`` verb and the HTTP
        ``/healthz`` endpoint both render exactly this dict."""
        return {
            "ok": True,
            "queue_depth": self.queue.depth(),
            "shed_state": self.controller.state,
            "breaker_state": getattr(self.breaker, "state", None),
        }

    def _telemetry(self, cmd: str, responder) -> None:
        from ..obs.telemetry import answer_cmd

        responder.send(answer_cmd(cmd, status=self.status()))

    # -- the scoring side --------------------------------------------------

    def _dispatch(self, block, staged=None, nxt=None):
        """Async-dispatch one superblock under its own shared retry
        budget (the per-superblock watchdog deadline rides inside the
        scorer, unchanged from batch mode).  A failure that escapes the
        whole retry/degrade ladder quarantines instead of killing the
        loop.

        ``staged`` is this block's prestaged feed handle (or None) and
        ``nxt`` the NEXT planned block of the tick: after the async
        dispatch goes out, ``nxt``'s host->device transfers are staged
        so they overlap this block's compute, and the new handle is
        returned for the caller to thread into the next call.

        With a fleet accepting (a live worker on the board), the block
        is OFFERED instead: the payload goes out under a fresh lease and
        the coordinator's pump collects the epoch-fenced result.  The
        poison check stays coordinator-side either way — quarantine
        bisection needs the session tags, which never cross the board."""
        if self.fleet is not None and self.fleet.accepting():
            try:
                self._check_poison(block)
            except Exception as e:
                self._block_failed(block, e)
                return None
            self.fleet.offer(block)
            publish(
                "serve.batch.dispatch",
                rows=block.real_rows,
                fill=round(block.fill_ratio, 4),
                depth=self.queue.depth(),
                links=block.link_ids(),
            )
            # Fleet path: no local compute to overlap with.
            return None
        budget = self.policy.new_budget()
        links = block.link_ids()
        try:
            self._check_poison(block)
            promise = self.pipeline.dispatch(
                block.seq1_codes, block.codes, block.weights, budget,
                links=links, staged=staged,
            )
        except Exception as e:
            self._block_failed(block, e)
            return None
        nstaged = (
            self.stager.stage(nxt.seq1_codes, nxt.codes, nxt.weights)
            if nxt is not None
            else None
        )
        publish(
            "serve.batch.dispatch",
            rows=block.real_rows,
            fill=round(block.fill_ratio, 4),
            depth=self.queue.depth(),
            links=links,
        )
        self.window.push(promise, block, budget)
        return nstaged

    def _finish(self, promise, block, budget) -> None:
        """Materialise one superblock and demux rows to sessions by tag
        (pad rows carry a ``None`` tag and are dropped)."""
        try:
            rows = self.pipeline.materialise(
                promise, block.seq1_codes, block.codes, block.weights, budget
            )
        except Exception as e:
            self._block_failed(block, e)
            return
        self._demux(rows, block)

    def _demux(self, rows, block) -> None:
        with span("serve.request.emit"):
            for row, tag in zip(rows, block.tags):
                if tag is not None:
                    sess, j = tag
                    sess.fill(j, row)
        if self._steady_base is None:
            # Baseline AFTER the first block: its compiles are the warmup;
            # everything later must be cache hits (ROADMAP Open item 5).
            # A prewarmed loop never reaches this — baseline_steady()
            # already pinned the baseline at tick 0.
            self._steady_base = compile_count()

    # -- poison-request quarantine ----------------------------------------

    def _check_poison(self, block) -> None:
        """Chaos marker: a poisoned session makes every superblock that
        contains it fail FATALLY (ValueError — skips retry and degrade),
        so the quarantine bisection below is what has to save its
        co-batched victims."""
        for tag in block.tags:
            if tag is not None and getattr(tag[0], "poisoned", False):
                raise InjectedFatalFaultError(
                    f"poisoned session {tag[0].id!r} co-batched in this "
                    "superblock"
                )

    def _block_failed(self, block, err) -> None:
        """Quarantine stage 1: a superblock failed past its whole
        retry/degrade ladder.  One synchronous whole-block retry under a
        fresh budget (transient wedges clear); a block that fails twice
        is bisected by session so the poison is isolated and its
        co-batched victims are re-planned onto clean blocks."""
        publish("serve.block.failed", rows=block.real_rows, error=str(err))
        log_line(
            f"mpi_openmp_cuda_tpu: serve: superblock failed ({err}); "
            "retrying the whole block before bisection"
        )
        try:
            self._score_block_sync(block)
        except Exception as e:
            self._bisect(block, e)

    def _score_block_sync(self, block) -> None:
        """Score one superblock synchronously under a fresh budget and
        demux — the quarantine path's unit of work."""
        self._check_poison(block)
        budget = self.policy.new_budget()
        promise = self.pipeline.dispatch(
            block.seq1_codes, block.codes, block.weights, budget,
            links=block.link_ids(),
        )
        rows = self.pipeline.materialise(
            promise, block.seq1_codes, block.codes, block.weights, budget
        )
        self._demux(rows, block)

    def _fleet_fallback(self, block) -> None:
        """Coordinator-local scoring for a fleet superblock with no live
        workers (or at drain): the same sync score → retry → bisection
        quarantine ladder as any failed local block."""
        try:
            self._score_block_sync(block)
        except Exception as e:
            self._block_failed(block, e)

    def _bisect(self, block, err) -> None:
        """Quarantine stage 2: split the failed block's sessions in half
        and score each half on its own padded block, recursing on
        failure.  A block that fails twice with ONE session left holds
        the poison: that session is answered with a typed error and the
        recursion ends — every other session was already re-planned onto
        a block that scored clean."""
        groups: list[tuple] = []  # (session, [(j, codes), ...]) in order
        index: dict[int, tuple] = {}
        for tag, codes in zip(block.tags, block.codes):
            if tag is None:
                continue
            sess, j = tag
            if sess.closed:
                continue
            g = index.get(id(sess))
            if g is None:
                g = index[id(sess)] = (sess, [])
                groups.append(g)
            g[1].append((j, codes))
        if not groups:
            return
        if len(groups) == 1:
            sess = groups[0][0]
            publish("serve.request.poisoned", id=sess.id)
            log_line(
                f"mpi_openmp_cuda_tpu: serve: quarantined poison request "
                f"{sess.id!r} ({err})"
            )
            sess.fail(f"poison: superblock failed twice in isolation ({err})")
            return
        mid = (len(groups) + 1) // 2
        for half in (groups[:mid], groups[mid:]):
            sub = self._subblock(block, half)
            try:
                self._score_block_sync(sub)
            except Exception as e:
                self._bisect(sub, e)

    def _subblock(self, block, groups) -> SuperBlock:
        """Re-plan a subset of a failed block's sessions into a fresh
        block of the SAME fixed shape (rows_per_block x the parent's
        bucket), so quarantine dispatches stay on warm jit caches."""
        members = [
            (sess, j, codes) for sess, rows in groups for (j, codes) in rows
        ]
        pad_len = min(max(c.size for (_, _, c) in members), BUF_SIZE_SEQ2)
        pad = np.ones(pad_len, dtype=np.int8)
        n_pad = max(0, self.rows_per_block - len(members))
        return SuperBlock(
            weights=block.weights,
            seq1_codes=block.seq1_codes,
            codes=[c for (_, _, c) in members] + [pad] * n_pad,
            tags=[(s, j) for (s, j, _) in members] + [None] * n_pad,
            real_rows=len(members),
        )

    def baseline_steady(self) -> None:
        """Pin the steady-compile baseline NOW — called after a prewarm,
        BEFORE the first tick, so the very first block is already held
        to the zero-recompile standard instead of being absorbed as
        warmup.  Exports ``serve_prewarmed`` so the smoke gate can
        verify the strict baseline was actually armed."""
        self._steady_base = compile_count()
        obs_gauge("serve_prewarmed", 1)

    def _release_session(self, sess) -> None:
        """Session ``on_close``: return its admission-bucket tokens (the
        token bucket refills on completions, keeping admission
        deterministic)."""
        self.controller.release(sess.cost_s)

    def _admit_sessions(self, sessions, now: float) -> list:
        """Deadline/abandonment checkpoint at batch planning: a session
        already past its deadline — or whose modelled wall cannot fit
        the remaining budget — is answered with the typed ``deadline``
        error instead of occupying superblock rows; a session whose
        client vanished is retired silently (its queue cost releases
        either way)."""
        live = []
        for sess in sessions:
            if sess.closed:
                continue
            if sess.abandoned:
                sess.abandon()
                continue
            if sess.deadline_t is not None:
                remaining = sess.deadline_t - now
                if remaining <= 0.0 or sess.cost_s > remaining:
                    sess.fail(
                        "deadline", estimated_s=round(sess.cost_s, 6)
                    )
                    continue
            live.append(sess)
        return live

    def tick(self) -> bool:
        """One loop iteration; returns False once idle with no sources
        left (the stdin/file mode's termination condition)."""
        # kill:serve-tick rides this fire point: SIGKILL at a tick
        # boundary, where the live journal exactly holds the unanswered
        # set (chaos-kill tier proves no-lost + no-double on resume).
        _fault_fire("serve_tick")
        if drain_requested():
            self._drain(())
        window_s = (
            0.0 if self.controller.state == SHED_DRAIN else self.window_s
        )
        items = self.queue.pop_ready(
            _TICK_S, window_s, self.max_pop, wake=drain_requested
        )
        if drain_requested():
            # Popped-but-unstarted requests at the drain boundary are
            # "queued" for journal purposes: nothing was dispatched yet.
            self._drain(items)
        if self.breaker is not None:
            self.breaker.tick()
        if self.fleet is not None:
            self.fleet.pump(
                idle=not items and self.queue.depth() == 0
            )
        now = self.clock.now()
        if items:
            for item in items:
                wait = max(0.0, now - item.admitted_t)
                self.controller.observe_wait(wait)
                publish(
                    "serve.queue.wait",
                    wait_s=round(wait, 6),
                    trace=item.trace_id,
                )
        elif self.queue.depth() == 0:
            self.controller.note_idle()
        # The tick timestamp marks the controller's drain-rate window
        # (the measured retry_after_s hint); shed decisions stay
        # clock-free inside.
        self.controller.update_state(now)
        sessions = []
        for item in items:
            try:
                with span("serve.request.parse"):
                    sess = build_session(
                        item, self.clock, on_close=self._release_session
                    )
            except RequestError as e:
                publish(
                    "serve.request.rejected",
                    reason="invalid",
                    depth=self.queue.depth(),
                )
                item.responder.send(
                    {"id": item.raw.get("id"), "error": str(e)}
                )
                self.controller.release(item.cost_s)
                continue
            if _fault_scheduled("poison-session"):
                # Chaos marker: superblocks containing this session fail
                # fatally until quarantine isolates it.
                sess.poisoned = True
            sessions.append(sess)
            self._inflight.append((sess, item.raw))
        # Journal checkpoint A: popped-but-unanswered requests are now
        # tracked as in-flight — a death anywhere in this tick keeps
        # them journaled.
        self._journal_live()
        live = self._admit_sessions(sessions, now)
        if live:
            blocks = list(plan_blocks(live, self.rows_per_block))
            staged = None
            for i, block in enumerate(blocks):
                nxt = blocks[i + 1] if i + 1 < len(blocks) else None
                staged = self._dispatch(block, staged=staged, nxt=nxt)
            self.window.flush()
        for sess in sessions:
            # Emits the done record for empty (n == 0) requests; a
            # no-op for sessions already completed or failed.
            sess.advance()
        # Journal checkpoint B: requests answered this tick leave the
        # journal, so the next tick's kill cannot double-answer them.
        self._journal_live()
        obs_gauge("queue_depth", self.queue.depth())
        obs_gauge("shed_state", self.controller.state)
        return (
            bool(items)
            or not self.queue.idle()
            or (self.fleet is not None and self.fleet.outstanding() > 0)
        )

    def _note_answered(self, rid: str) -> None:
        """Record one answered reply id in the bounded dedupe window."""
        if rid in self._answered_set:
            return
        if len(self._answered) == self._answered.maxlen:
            self._answered_set.discard(self._answered[0])
        self._answered.append(rid)
        self._answered_set.add(rid)

    def _journal_live(self) -> None:
        """Rewrite the serve journal (whole-file atomic) with every
        admitted-but-unanswered raw request — in-flight first (older),
        then still-queued — skipping the write when nothing changed.
        The drain path's :func:`journal_drained` call stays the final
        authoritative write; this keeps the file honest BETWEEN drains
        so ``kill -9`` + ``--resume`` loses and doubles nothing.

        The same checkpoint, as fleet LEADER, also goes to the board
        (:meth:`.fleet.FleetCoordinator.checkpoint`): unanswered raws
        plus the answered-id set — everything a standby needs to take
        over with zero dropped and zero duplicated replies."""
        kept = []
        for sess, raw in self._inflight:
            if not sess.closed:
                kept.append((sess, raw))
                continue
            if sess.answered:
                rid = raw.get("id")
                if rid is not None:
                    self._note_answered(str(rid))
        self._inflight = kept
        fleet_leader = self.fleet is not None and self.fleet.leader is not None
        if self.journal_path is None and not fleet_leader:
            return
        raws = [raw for (_sess, raw) in self._inflight]
        raws += self.queue.snapshot_raws()
        if fleet_leader:
            self.fleet.checkpoint(raws, self._answered)
        if self.journal_path is None:
            return
        state = json.dumps(raws)
        if state == self._journal_state:
            return
        self._journal_state = state
        journal_drained(self.journal_path, raws)

    # -- drain -------------------------------------------------------------

    def _drain(self, popped) -> None:
        """Close admission, finish in-flight work, journal the leftovers,
        and surface the resumable preemption (CLI maps it to exit 75)."""
        self.queue.close()
        self.window.flush()
        if self.fleet is not None:
            # Fence + locally finish fleet superblocks still in flight:
            # their sessions answer BEFORE the journal write below, and
            # any straggler worker post lands on a bumped epoch.
            self.fleet.finish_locally()
        leftovers = list(popped) + self.queue.drain_pending()
        for it in leftovers:
            it.responder.send({"id": it.raw.get("id"), "drained": True})
        n = len(leftovers)
        if self.journal_path is not None:
            journal_drained(self.journal_path, [it.raw for it in leftovers])
            raise DrainInterrupt(
                f"serve loop preempted; {n} queued request(s) journaled — "
                f"rerun with --serve --journal {self.journal_path} "
                "--resume to finish them"
            )
        raise DrainInterrupt(
            f"serve loop preempted; no --journal, so {n} queued "
            "request(s) are dropped (clients were sent drained notices)"
        )

    def record_steady_gauge(self) -> None:
        """Export the steady-state recompile delta (0 until any block
        has finished — an idle server has nothing to be cold about)."""
        base = self._steady_base
        obs_gauge(
            "serve_steady_compiles",
            0 if base is None else compile_count() - base,
        )


# -- transports --------------------------------------------------------------


def _serve_connection(loop: ServeLoop, conn) -> None:
    """One client connection's reader thread: lines in, queue in; the
    responder (writer side) is driven from the main loop thread.  The
    connection stays open after client EOF so pending results flow; a
    client that disconnects hard just deadens its responder.

    Slow-client armor: a send timeout (SO_SNDTIMEO — NOT
    ``conn.settimeout``, which would also time out this thread's
    blocking reads) bounds how long a full client socket buffer can
    stall the main loop's emit path; a timed-out write raises OSError
    in ``Responder.send`` and the client is classified dead.

    Each connection holds ONE queue source while its reader lives or
    its responder is healthy; whichever dies first releases it exactly
    once, so a vanished client cannot pin the queue's source refcount
    (or, through it, the gather window) until drain.
    """
    timeout_s = env_float("SEQALIGN_SERVE_WRITE_TIMEOUT_S", 5.0)
    if timeout_s and timeout_s > 0:
        tv = struct.pack(
            "ll", int(timeout_s), int((timeout_s % 1.0) * 1e6)
        )
        try:
            conn.setsockopt(socketlib.SOL_SOCKET, socketlib.SO_SNDTIMEO, tv)
        except (OSError, ValueError):  # pragma: no cover - platform quirk
            pass
    rfile = conn.makefile("r", encoding="utf-8", newline="\n")
    wfile = conn.makefile("w", encoding="utf-8", newline="\n")
    state = {"released": False}
    release_lock = threading.Lock()

    def _release() -> None:
        with release_lock:
            if state["released"]:
                return
            state["released"] = True
        loop.queue.close_source()

    responder = Responder(wfile, on_dead=_release)
    loop.queue.open_source()
    try:
        for line in rfile:
            loop.ingest(line, responder)
    except (OSError, ValueError):
        pass
    finally:
        _release()


def _accept_loop(loop: ServeLoop, sock) -> None:
    """The listener thread: accept → spawn a daemon reader per client."""
    while True:
        try:
            conn, _addr = sock.accept()
        except OSError:
            return  # listener closed: the run is over
        threading.Thread(
            target=_serve_connection, args=(loop, conn), daemon=True
        ).start()


def _standby_phase(loop: ServeLoop, board, leader, out_responder) -> bool:
    """The ``--fleet-standby`` serve phase: watch the active leader's
    beat until a verdict.  Returns True once THIS process holds the
    leadership (the caller then runs the normal tick loop as the
    successor coordinator) and False on a clean exit — the fleet shut
    down, or this standby was drain-signalled while empty.

    Takeover sequence (all before the first tick): claim the next
    generation (done inside ``standby_wait``), build the successor
    coordinator, seed the answered-id set from the dead leader's
    checkpoint, and re-ingest its unanswered raw requests through the
    normal admission path.  The answered set makes the replay — and any
    client redriving its own requests afterwards — idempotent: zero
    dropped, zero duplicated reply lines.
    """
    from ..resilience.membership import read_checkpoint
    from .fleet import FleetCoordinator, standby_wait

    verdict, watched = standby_wait(board, leader, loop.clock)
    if verdict != "takeover":
        log_line(
            f"mpi_openmp_cuda_tpu: serve: standby exiting ({verdict}): "
            "nothing to take over"
        )
        if verdict == "drain" and loop.queue.depth() > 0:
            loop._drain(())  # raises DrainInterrupt → the CLI's exit 75
        return False
    publish(
        "leader.takeover", gen=leader.gen, prev=watched, leader=leader.lid
    )
    obs_gauge("fleet_leader_epoch", leader.gen)
    log_line(
        f"mpi_openmp_cuda_tpu: serve: standby took over as leader gen "
        f"{leader.gen} (gen {watched} went silent)"
    )
    loop.fleet = FleetCoordinator(
        board,
        local_score=loop._fleet_fallback,
        demux=loop._demux,
        clock=loop.clock,
        leader=leader,
    )
    obs_gauge("fleet_workers", 0)
    ckpt = read_checkpoint(board, watched)
    if ckpt is None:
        log_line(
            "mpi_openmp_cuda_tpu: serve: no readable checkpoint from "
            f"gen {watched}; serving fresh traffic only"
        )
        return True
    for rid in ckpt["answered"]:
        loop._note_answered(str(rid))
    replayed = 0
    loop.queue.open_source()
    try:
        for raw in ckpt["requests"]:
            if not isinstance(raw, dict):
                continue
            rid = raw.get("id")
            if rid is not None and str(rid) in loop._answered_set:
                continue  # the dead leader answered it; don't re-reply
            loop.ingest(json_dumps_line(raw), out_responder)
            replayed += 1
    finally:
        loop.queue.close_source()
    log_line(
        f"mpi_openmp_cuda_tpu: serve: replayed {replayed} unanswered "
        f"request(s) from gen {watched}'s checkpoint "
        f"({len(ckpt['answered'])} already answered)"
    )
    # Re-checkpoint under OUR generation before the first tick: a kill
    # during takeover must not lose what we just admitted.
    loop._journal_live()
    return True


def run_serve(args, timer, policy, deg, out_stream=None, prewarmed=False) -> int:
    """CLI entry for ``--serve`` (called with the observability plane,
    faults, watchdog, and drain guard already armed by ``run()``).

    Sources: ``--port`` opens a loopback ndjson socket (port 0 → the
    OS assigns; the bound port is announced on stderr).  Without a port
    — or with an explicit ``--input`` — requests are read line-by-line
    from the file/stdin on the main thread and the loop runs until the
    queue drains, which makes pipe mode fully deterministic for tests.

    ``prewarmed=True`` (the CLI ran the AOT prewarm) pins the steady-
    compile baseline before any tick, so the recompile gate covers the
    first request too.
    """
    from ..io.pipeline import ChunkPipeline
    from ..io.parse import open_input

    breaker = None
    if deg is not None and deg.enabled:
        from ..resilience.breaker import STATE_CLOSED, CircuitBreaker

        breaker = CircuitBreaker(
            deg,
            threshold=env_int("SEQALIGN_BREAKER_THRESHOLD", 3),
            window_ticks=env_int("SEQALIGN_BREAKER_WINDOW", 16),
            cooldown_ticks=env_int("SEQALIGN_BREAKER_COOLDOWN", 8),
        )
        obs_gauge("breaker_state", STATE_CLOSED)
    loop = ServeLoop(
        ChunkPipeline(policy, deg, breaker=breaker),
        policy,
        journal_path=args.journal,
    )
    if prewarmed:
        loop.baseline_steady()
    standby = bool(getattr(args, "fleet_standby", False))
    board = None
    leader = None
    if getattr(args, "fleet_board", None):
        from ..resilience.membership import LeaderLease, shutdown_key
        from ..resilience.rescue import FileBoard
        from .fleet import FleetCoordinator, lease_ticks_for

        board = FileBoard(args.fleet_board)
        leader = LeaderLease(board, f"c{os.getpid()}", lease_ticks_for())
        if standby:
            log_line(
                "mpi_openmp_cuda_tpu: serve: standby watching board "
                f"{args.fleet_board!r} (leader deadline "
                f"{leader.deadline_ticks} ticks)"
            )
        else:
            # A reused board may hold a finished run's shutdown key —
            # it would retire this run's workers/standbys on sight.
            board.delete(shutdown_key())
            gen = leader.acquire()
            obs_gauge("fleet_leader_epoch", gen)
            loop.fleet = FleetCoordinator(
                board,
                local_score=loop._fleet_fallback,
                demux=loop._demux,
                clock=loop.clock,
                leader=leader,
            )
            obs_gauge("fleet_workers", 0)
            log_line(
                "mpi_openmp_cuda_tpu: serve: fleet coordinator on board "
                f"{args.fleet_board!r} as leader gen {gen} "
                f"(lease {loop.fleet.lease_ticks} ticks)"
            )
    out_responder = Responder(out_stream or sys.stdout)
    if args.journal:
        resumed = load_drained(args.journal)
        if resumed:
            log_line(
                f"mpi_openmp_cuda_tpu: serve journal {args.journal!r}: "
                f"re-admitting {len(resumed)} drained request(s)"
            )
        for raw in resumed:
            loop.ingest(json_dumps_line(raw), out_responder)

    port = args.port if args.port is not None else env_int("SEQALIGN_SERVE_PORT")
    persistent = port is not None
    telemetry_port = getattr(args, "telemetry_port", None)
    if telemetry_port is None:
        telemetry_port = env_int("SEQALIGN_TELEMETRY_PORT")
    sock = None
    telem = None
    try:
        if telemetry_port is not None:
            from ..obs.telemetry import TelemetryServer

            telem = TelemetryServer(int(telemetry_port), status=loop.status)
            log_line(
                "mpi_openmp_cuda_tpu: telemetry on "
                f"127.0.0.1:{telem.start()}"
            )
        if persistent:
            sock = socketlib.create_server(("127.0.0.1", int(port)))
            bound = sock.getsockname()[1]
            log_line(f"mpi_openmp_cuda_tpu: serving on 127.0.0.1:{bound}")
            loop.queue.open_source()
            threading.Thread(
                target=_accept_loop, args=(loop, sock), daemon=True
            ).start()
        serving = True
        with timer.phase("serve"):
            if standby:
                serving = _standby_phase(loop, board, leader, out_responder)
            if serving:
                if (not persistent or args.input is not None) and not standby:
                    loop.queue.open_source()
                    try:
                        with open_input(args.input) as stream:
                            for line in stream:
                                loop.ingest(line, out_responder)
                                if drain_requested():
                                    break
                    finally:
                        loop.queue.close_source()
                    # Checkpoint the freshly-queued raws BEFORE the first
                    # tick: a leader killed at its very first pump must
                    # already have them on the board for the standby.
                    loop._journal_live()
                while True:
                    alive = loop.tick()
                    if not persistent and not alive:
                        break
        if serving and args.journal:
            # Clean completion: nothing pending — rewrite the journal
            # empty so a later --resume re-admits nothing.
            journal_drained(args.journal, [])
        if serving and loop.fleet is not None:
            # Force-sweep the board: a completed run leaves no offer/
            # claim/result/checkpoint debris behind (fleet-chaos gates
            # on this), only the generation record and worker registry.
            loop.fleet.gc_final()
        timer.report()
        return 0
    finally:
        if loop.fleet is not None:
            loop.fleet.shutdown()
        loop.record_steady_gauge()
        if telem is not None:
            telem.close()
        if sock is not None:
            try:
                sock.close()
            except OSError:  # pragma: no cover - teardown best-effort
                pass


def json_dumps_line(raw: dict) -> str:
    """Round-trip a journaled raw request back through the normal ingest
    path (one line of JSON), so resume and live traffic share every
    validation/admission branch."""
    import json

    return json.dumps(raw)
