"""The serve loop: warm caches, continuous batching, drain → 75.

One :class:`ServeLoop` owns the run: the admission queue, the pending
window, and the jit caches (held warm simply by the process living —
the scorer and its compiled shapes persist across requests, which is
the whole point of serving versus one-shot batch runs).

A **tick** is the unit of work: pop whatever coalesced in the gather
window, validate each raw request into a :class:`.session.Session`
(typed error record on failure — the loop outlives bad input), plan
the pooled rows into fixed-shape superblocks, dispatch every block
through the shared :class:`..io.pipeline.ChunkPipeline` (async, windowed,
prefetched), then flush and demux rows back to sessions by tag.  Every
dispatch rides the SAME retry/degrade/watchdog machinery as the batch
CLI — a deadline-expired superblock is retried, not wedged.

**Drain**: the PR-4 guard's SIGTERM flag is checked at tick boundaries
and inside the queue wait (bounded, via the injectable clock — worst
case one tick of latency).  On drain: admission closes, in-flight
superblocks finish and their lines stream out, queued-but-unstarted
requests are journaled (whole-file atomic serve journal) and notified
``{"drained": true}``, and :class:`DrainInterrupt` surfaces → the CLI's
exit 75.  ``--serve --journal P --resume`` re-admits the journaled
requests before reading any new input.

**Steady-state compiles**: the PR-3 recompile detector baselines after
the first block finishes; everything after must hit warm caches.  The
delta is exported as the ``serve_steady_compiles`` gauge —
``make serve-smoke`` gates on it being 0.  Under ``--prewarm`` the AOT
warm plane compiles the block shapes BEFORE the loop starts and
:meth:`ServeLoop.baseline_steady` pins the baseline at tick 0 — the
first block is no longer a grace period, and ``make aot-smoke`` gates
the stricter contract.

Threading: socket reader threads only ``json.loads`` + enqueue (see
:mod:`.queue`); parsing, scoring, span recording, and ALL journal/metric
mutation happen on the main loop thread.
"""

from __future__ import annotations

import socket as socketlib
import sys
import threading

from ..analysis.recompile import compile_count
from ..io.pipeline import PendingWindow
from ..obs.events import log_line, publish
from ..obs.metrics import gauge as obs_gauge
from ..obs.spans import span
from ..resilience.drain import DrainInterrupt, drain_requested
from ..utils.platform import env_float, env_int
from .batcher import DEFAULT_BLOCK_ROWS, plan_blocks
from .clock import ServeClock
from .queue import ADMIT_CLOSED, ADMIT_FULL, RequestQueue
from .session import (
    RequestError,
    Responder,
    build_session,
    journal_drained,
    load_drained,
    parse_raw,
)

#: Upper bound on one queue wait: the drain flag is re-checked at least
#: this often even if no request ever arrives.
_TICK_S = 0.25


class ServeLoop:
    """The serving run's state: queue, window, pipeline, drain plumbing."""

    def __init__(
        self,
        pipeline,
        policy,
        *,
        clock=None,
        journal_path: str | None = None,
        max_depth: int | None = None,
        window_s: float | None = None,
        rows_per_block: int | None = None,
        max_pop: int | None = None,
    ):
        self.pipeline = pipeline
        self.policy = policy
        self.clock = clock or ServeClock()
        self.journal_path = journal_path
        self.window_s = (
            window_s
            if window_s is not None
            else env_float("SEQALIGN_SERVE_WINDOW_S", 0.05)
        )
        self.rows_per_block = (
            rows_per_block
            if rows_per_block is not None
            else env_int("SEQALIGN_SERVE_BLOCK_ROWS", DEFAULT_BLOCK_ROWS)
        )
        self.max_pop = (
            max_pop
            if max_pop is not None
            else env_int("SEQALIGN_SERVE_MAX_POP", 0)
        )
        self.queue = RequestQueue(
            max_depth
            if max_depth is not None
            else env_int("SEQALIGN_SERVE_MAX_QUEUE", 256),
            self.clock,
        )
        self.window = PendingWindow(
            max(1, env_int("TPU_SEQALIGN_STREAM_DEPTH", 4)), self._finish
        )
        self._steady_base: int | None = None

    # -- ingest (reader threads and the main-thread stdin loop) -----------

    def ingest(self, line: str, responder) -> None:
        """One wire line → parse-to-dict → admission; error record on a
        line that is not a JSON object, backpressure/drain verdicts
        relayed to the client."""
        line = line.strip()
        if not line:
            return
        try:
            raw = parse_raw(line)
        except RequestError as e:
            publish(
                "serve.request.rejected",
                reason="malformed",
                depth=self.queue.depth(),
            )
            responder.send({"id": None, "error": str(e)})
            return
        verdict = self.queue.submit(raw, responder)
        if verdict == ADMIT_FULL:
            responder.send(
                {
                    "id": raw.get("id"),
                    "error": f"queue full ({self.queue.max_depth} requests "
                    "queued); resubmit later",
                }
            )
        elif verdict == ADMIT_CLOSED:
            responder.send(
                {
                    "id": raw.get("id"),
                    "error": "server is draining; resubmit elsewhere",
                }
            )

    # -- the scoring side --------------------------------------------------

    def _dispatch(self, block) -> None:
        """Async-dispatch one superblock under its own shared retry
        budget (the per-superblock watchdog deadline rides inside the
        scorer, unchanged from batch mode)."""
        budget = self.policy.new_budget()
        promise = self.pipeline.dispatch(
            block.seq1_codes, block.codes, block.weights, budget
        )
        publish(
            "serve.batch.dispatch",
            rows=block.real_rows,
            fill=round(block.fill_ratio, 4),
            depth=self.queue.depth(),
        )
        self.window.push(promise, block, budget)

    def _finish(self, promise, block, budget) -> None:
        """Materialise one superblock and demux rows to sessions by tag
        (pad rows carry a ``None`` tag and are dropped)."""
        rows = self.pipeline.materialise(
            promise, block.seq1_codes, block.codes, block.weights, budget
        )
        with span("serve.request.emit"):
            for row, tag in zip(rows, block.tags):
                if tag is not None:
                    sess, j = tag
                    sess.fill(j, row)
        if self._steady_base is None:
            # Baseline AFTER the first block: its compiles are the warmup;
            # everything later must be cache hits (ROADMAP Open item 5).
            # A prewarmed loop never reaches this — baseline_steady()
            # already pinned the baseline at tick 0.
            self._steady_base = compile_count()

    def baseline_steady(self) -> None:
        """Pin the steady-compile baseline NOW — called after a prewarm,
        BEFORE the first tick, so the very first block is already held
        to the zero-recompile standard instead of being absorbed as
        warmup.  Exports ``serve_prewarmed`` so the smoke gate can
        verify the strict baseline was actually armed."""
        self._steady_base = compile_count()
        obs_gauge("serve_prewarmed", 1)

    def tick(self) -> bool:
        """One loop iteration; returns False once idle with no sources
        left (the stdin/file mode's termination condition)."""
        if drain_requested():
            self._drain(())
        items = self.queue.pop_ready(
            _TICK_S, self.window_s, self.max_pop, wake=drain_requested
        )
        if drain_requested():
            # Popped-but-unstarted requests at the drain boundary are
            # "queued" for journal purposes: nothing was dispatched yet.
            self._drain(items)
        sessions = []
        for item in items:
            try:
                with span("serve.request.parse"):
                    sess = build_session(item, self.clock)
            except RequestError as e:
                publish(
                    "serve.request.rejected",
                    reason="invalid",
                    depth=self.queue.depth(),
                )
                item.responder.send(
                    {"id": item.raw.get("id"), "error": str(e)}
                )
                continue
            sessions.append(sess)
        if sessions:
            for block in plan_blocks(sessions, self.rows_per_block):
                self._dispatch(block)
            self.window.flush()
            for sess in sessions:
                # Emits the done record for empty (n == 0) requests; a
                # no-op for sessions already completed through demux.
                sess.advance()
        obs_gauge("queue_depth", self.queue.depth())
        return bool(items) or not self.queue.idle()

    # -- drain -------------------------------------------------------------

    def _drain(self, popped) -> None:
        """Close admission, finish in-flight work, journal the leftovers,
        and surface the resumable preemption (CLI maps it to exit 75)."""
        self.queue.close()
        self.window.flush()
        leftovers = list(popped) + self.queue.drain_pending()
        for it in leftovers:
            it.responder.send({"id": it.raw.get("id"), "drained": True})
        n = len(leftovers)
        if self.journal_path is not None:
            journal_drained(self.journal_path, [it.raw for it in leftovers])
            raise DrainInterrupt(
                f"serve loop preempted; {n} queued request(s) journaled — "
                f"rerun with --serve --journal {self.journal_path} "
                "--resume to finish them"
            )
        raise DrainInterrupt(
            f"serve loop preempted; no --journal, so {n} queued "
            "request(s) are dropped (clients were sent drained notices)"
        )

    def record_steady_gauge(self) -> None:
        """Export the steady-state recompile delta (0 until any block
        has finished — an idle server has nothing to be cold about)."""
        base = self._steady_base
        obs_gauge(
            "serve_steady_compiles",
            0 if base is None else compile_count() - base,
        )


# -- transports --------------------------------------------------------------


def _serve_connection(loop: ServeLoop, conn) -> None:
    """One client connection's reader thread: lines in, queue in; the
    responder (writer side) is driven from the main loop thread.  The
    connection stays open after client EOF so pending results flow; a
    client that disconnects hard just deadens its responder."""
    rfile = conn.makefile("r", encoding="utf-8", newline="\n")
    wfile = conn.makefile("w", encoding="utf-8", newline="\n")
    responder = Responder(wfile)
    try:
        for line in rfile:
            loop.ingest(line, responder)
    except (OSError, ValueError):
        pass


def _accept_loop(loop: ServeLoop, sock) -> None:
    """The listener thread: accept → spawn a daemon reader per client."""
    while True:
        try:
            conn, _addr = sock.accept()
        except OSError:
            return  # listener closed: the run is over
        threading.Thread(
            target=_serve_connection, args=(loop, conn), daemon=True
        ).start()


def run_serve(args, timer, policy, deg, out_stream=None, prewarmed=False) -> int:
    """CLI entry for ``--serve`` (called with the observability plane,
    faults, watchdog, and drain guard already armed by ``run()``).

    Sources: ``--port`` opens a loopback ndjson socket (port 0 → the
    OS assigns; the bound port is announced on stderr).  Without a port
    — or with an explicit ``--input`` — requests are read line-by-line
    from the file/stdin on the main thread and the loop runs until the
    queue drains, which makes pipe mode fully deterministic for tests.

    ``prewarmed=True`` (the CLI ran the AOT prewarm) pins the steady-
    compile baseline before any tick, so the recompile gate covers the
    first request too.
    """
    from ..io.pipeline import ChunkPipeline
    from ..io.parse import open_input

    loop = ServeLoop(
        ChunkPipeline(policy, deg), policy, journal_path=args.journal
    )
    if prewarmed:
        loop.baseline_steady()
    out_responder = Responder(out_stream or sys.stdout)
    if args.journal:
        resumed = load_drained(args.journal)
        if resumed:
            log_line(
                f"mpi_openmp_cuda_tpu: serve journal {args.journal!r}: "
                f"re-admitting {len(resumed)} drained request(s)"
            )
        for raw in resumed:
            loop.ingest(json_dumps_line(raw), out_responder)

    port = args.port if args.port is not None else env_int("SEQALIGN_SERVE_PORT")
    persistent = port is not None
    sock = None
    try:
        if persistent:
            sock = socketlib.create_server(("127.0.0.1", int(port)))
            bound = sock.getsockname()[1]
            log_line(f"mpi_openmp_cuda_tpu: serving on 127.0.0.1:{bound}")
            loop.queue.open_source()
            threading.Thread(
                target=_accept_loop, args=(loop, sock), daemon=True
            ).start()
        with timer.phase("serve"):
            if not persistent or args.input is not None:
                loop.queue.open_source()
                try:
                    with open_input(args.input) as stream:
                        for line in stream:
                            loop.ingest(line, out_responder)
                            if drain_requested():
                                break
                finally:
                    loop.queue.close_source()
            while True:
                alive = loop.tick()
                if not persistent and not alive:
                    break
        if args.journal:
            # Clean completion: nothing pending — rewrite the journal
            # empty so a later --resume re-admits nothing.
            journal_drained(args.journal, [])
        timer.report()
        return 0
    finally:
        loop.record_steady_gauge()
        if sock is not None:
            try:
                sock.close()
            except OSError:  # pragma: no cover - teardown best-effort
                pass


def json_dumps_line(raw: dict) -> str:
    """Round-trip a journaled raw request back through the normal ingest
    path (one line of JSON), so resume and live traffic share every
    validation/admission branch."""
    import json

    return json.dumps(raw)
