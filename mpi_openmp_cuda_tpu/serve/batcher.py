"""Bucketed continuous batching: concurrent requests → shared superblocks.

The batch CLI pads each problem to its own bucket shapes; a server that
did that per request would pay one (mostly-padding) dispatch per client.
Here Seq2 rows from EVERY session popped in one tick are pooled:

1. group by *problem key* ``(weights, seq1)`` — rows are only
   co-scorable when they share the scorer's other two inputs;
2. inside a group, run the existing length-bucket planner
   (:func:`..ops.dispatch.plan_buckets`, ``packable=False`` /
   ``min_rows=1``: no straggler merging — a merged row would change its
   L2P and with it the compiled shape);
3. chop each bucket into :class:`SuperBlock`\\ s of exactly
   ``rows_per_block`` rows, padding the tail block with throwaway rows
   of the SAME bucket length.

Step 3 is the steady-state-compile guarantee: every block the loop ever
dispatches has shape ``[rows_per_block, l2p]`` for a bucketed ``l2p``,
so after the first block of a given ``(seq1-bucket, l2p)`` the jit cache
is warm and ``make serve-smoke``'s recompile gate (PR-3 detector) holds
at zero.  Pad rows are scored (wasted lanes, counted by
``fill_ratio``) and dropped at demux via their ``None`` tag.

Each real row's tag is ``(session, local_index)``: results demux back
to the right client in the right per-request order no matter how
requests interleaved inside the block.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..ops.dispatch import plan_buckets
from ..utils.constants import BUF_SIZE_SEQ2

#: Rows per dispatched superblock (SEQALIGN_SERVE_BLOCK_ROWS overrides;
#: power of two keeps choose_chunk's pow2 flooring exact).
DEFAULT_BLOCK_ROWS = 64


@dataclasses.dataclass
class SuperBlock:
    """One fixed-shape dispatch unit: the shared problem key, the padded
    row list, and the demux tags (``None`` marks a pad row)."""

    weights: list[int]
    seq1_codes: np.ndarray
    codes: list[np.ndarray]
    tags: list[tuple | None]
    real_rows: int

    @property
    def fill_ratio(self) -> float:
        return self.real_rows / max(1, len(self.codes))

    def link_ids(self) -> list[str]:
        """Request ids whose rows ride this block, first-row order,
        deduplicated — the many-to-one trace links a shared-superblock
        dispatch span carries (obs/trace.py)."""
        out: list[str] = []
        seen: set[str] = set()
        for tag in self.tags:
            if tag is None:
                continue
            rid = str(tag[0].id)
            if rid not in seen:
                seen.add(rid)
                out.append(rid)
        return out

    def link_traces(self) -> list[str]:
        """Trace ids for the same rows (empty strings dropped: batch-
        and stream-mode callers have no admission-minted trace ids)."""
        out: list[str] = []
        seen: set[str] = set()
        for tag in self.tags:
            if tag is None:
                continue
            tid = str(getattr(tag[0], "trace_id", "") or "")
            if tid and tid not in seen:
                seen.add(tid)
                out.append(tid)
        return out


def plan_blocks(sessions, rows_per_block: int) -> list[SuperBlock]:
    """Plan the tick's superblocks from every popped session's rows."""
    if rows_per_block < 1:
        raise ValueError(
            f"rows_per_block must be >= 1, got {rows_per_block}"
        )
    groups: dict[tuple, list[tuple]] = {}
    for sess in sessions:
        if getattr(sess, "closed", False):
            # Retired mid-tick (deadline miss, quarantined poison,
            # abandoned client): its rows must not occupy blocks.
            continue
        key = (tuple(int(w) for w in sess.weights), sess.seq1)
        rows = groups.setdefault(key, [])
        for j, codes in enumerate(sess.seq2_codes):
            rows.append((sess, j, codes))
    blocks: list[SuperBlock] = []
    for (weights, _seq1), rows in groups.items():
        seq1_codes = rows[0][0].seq1_codes
        buckets = plan_buckets(
            [c.size for (_, _, c) in rows], packable=False, min_rows=1
        )
        for l2p in sorted(buckets):
            members = [rows[i] for i in sorted(buckets[l2p])]
            # Pad length stays inside the reference buffer cap while
            # keeping the same L2P bucket (round_up(2000,128) == 2048),
            # so the dispatcher sees ONE uniform group per block.
            pad = np.ones(min(int(l2p), BUF_SIZE_SEQ2), dtype=np.int8)
            for off in range(0, len(members), rows_per_block):
                chunk = members[off : off + rows_per_block]
                n_pad = rows_per_block - len(chunk)
                blocks.append(
                    SuperBlock(
                        weights=list(weights),
                        seq1_codes=seq1_codes,
                        codes=[c for (_, _, c) in chunk] + [pad] * n_pad,
                        tags=[(s, j) for (s, j, _) in chunk]
                        + [None] * n_pad,
                        real_rows=len(chunk),
                    )
                )
    return blocks
