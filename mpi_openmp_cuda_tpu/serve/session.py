"""Per-request lifecycle: validation, ordered emission, the serve journal.

The wire protocol is newline-delimited JSON in both directions (the
loopback socket and the stdin pipe speak the same records):

request   ``{"id": ..., "weights": [w1,w2,w3,w4], "seq1": "...",
            "seq2": ["...", ...]}`` — ``id`` optional (defaults to
            ``req-<seq>`` from the admission counter)
response  ``{"id": ..., "line": "#j: score: S, n: N, k: K"}`` per
            sequence (the ``line`` value is byte-identical to the batch
            CLI's stdout line for the same problem), then
            ``{"id": ..., "done": true, "n": N}``; malformed input gets
            ``{"id": ..., "error": "..."}`` and the loop lives on; a
            drain hands queued-but-unstarted requests
            ``{"id": ..., "drained": true}`` after journaling them.

Validation runs on the MAIN loop thread (under the ``serve.request
.parse`` span — the span recorder is single-threaded by construction)
and reuses the batch parser's header validation verbatim, so a weight
that the batch CLI would reject is rejected here with the same message.
A bad request raises :class:`RequestError` → one typed error record,
never process death (the batch fail-stop stance inverted: the server
outlives its worst client).

Result rows can land out of order (a request's short and long Seq2s sit
in different length buckets, so different superblocks finish at
different times); :class:`Session` buffers and emits the longest
consecutively-scored prefix, so each client sees its lines in index
order and their concatenation is byte-identical to batch-mode output.

The **serve journal** is the drain's resume token: a whole-file atomic
write of the raw request dicts still queued at preemption.  Its format
line is distinct from the batch/stream journals — the three are
mutually foreign and refuse each other's files.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np

from ..io.parse import _parse_header_tokens
from ..io.printer import format_result
from ..models.encoding import encode_normalized
from ..obs.events import publish
from ..resilience.faults import scheduled as _fault_scheduled
from ..utils.constants import BUF_SIZE_SEQ1, BUF_SIZE_SEQ2
from ..utils.platform import env_float


class RequestError(ValueError):
    """A malformed/invalid request: rejected with a typed error record."""


class Responder:
    """One output stream shared by a request's records, lock-serialised.

    Writes one compact JSON document per line.  A broken client (closed
    socket, vanished pipe) marks the responder dead and later records
    are dropped silently — a client that hung up forfeits its results;
    it must not take the loop (or other clients) down with it.
    """

    def __init__(self, out, on_dead=None):
        self._out = out
        self._lock = threading.Lock()
        self._dead = False
        self._on_dead = on_dead

    @property
    def dead(self) -> bool:
        return self._dead

    def mark_dead(self) -> None:
        """Classify this client dead (failed/timed-out write, chaos
        marker).  The ``on_dead`` callback fires exactly once, outside
        the lock — it re-enters the serve queue's source refcount."""
        notify = False
        with self._lock:
            if not self._dead:
                self._dead = True
                notify = True
        if notify and self._on_dead is not None:
            self._on_dead()

    def send(self, obj: dict) -> None:
        if _fault_scheduled("dead-socket-midstream"):
            # Chaos marker: the client vanished between records.
            publish("serve.client.lost", how="dead-socket")
            self.mark_dead()
            return
        if _fault_scheduled("slow-client"):
            # Chaos marker: a stalled reader whose socket buffer never
            # drains — the SO_SNDTIMEO armor's classification, without
            # holding the loop for the real timeout.
            publish("serve.client.lost", how="slow-client")
            self.mark_dead()
            return
        died = False
        with self._lock:
            if self._dead:
                return
            try:
                self._out.write(json.dumps(obj) + "\n")
                self._out.flush()
            except (OSError, ValueError):
                # socket.timeout is an OSError: a write that cannot make
                # progress within SEQALIGN_SERVE_WRITE_TIMEOUT_S lands
                # here too.
                self._dead = True
                died = True
        if died:
            publish("serve.client.lost", how="write-failed")
            if self._on_dead is not None:
                self._on_dead()


def parse_raw(line: str) -> dict:
    """Reader-thread half of parsing: bytes → dict, nothing more."""
    try:
        raw = json.loads(line)
    except json.JSONDecodeError as e:
        raise RequestError(f"malformed request line (not JSON): {e}") from None
    if not isinstance(raw, dict):
        raise RequestError(
            f"request must be a JSON object, got {type(raw).__name__}"
        )
    return raw


class Session:
    """One validated in-flight request: its problem, its result rows,
    and the emit cursor that keeps output in per-request index order."""

    def __init__(
        self, req_id, weights, seq1, seq1_codes, seq2_codes, responder,
        admitted_t, clock, deadline_t=None, cost_s=0.0, on_close=None,
        trace_id="",
    ):
        self.id = req_id
        self.trace_id = trace_id  # minted at admission (obs/trace.py)
        self.weights = weights
        self.seq1 = seq1
        self.seq1_codes = seq1_codes
        self.seq2_codes = seq2_codes
        self.responder = responder
        self._admitted_t = admitted_t
        self._clock = clock
        self.deadline_t = deadline_t  # absolute clock time, None = no SLO
        self.cost_s = cost_s  # modelled wall charged at admission
        self.poisoned = False  # chaos marker: superblocks with me fail
        self.failed = None  # typed terminal error, if any
        self._on_close = on_close
        n = len(seq2_codes)
        self.rows = np.zeros((n, 3), dtype=np.int64)
        self._have = [False] * n
        self._emitted = 0
        self._done = False

    @property
    def count(self) -> int:
        return len(self.seq2_codes)

    @property
    def closed(self) -> bool:
        """Terminal (done record sent, typed failure, or abandoned):
        this session may not occupy superblock rows any more — the
        batcher skips it when (re-)planning."""
        return self._done

    @property
    def abandoned(self) -> bool:
        """The client is gone (dead responder): nobody reads the rows."""
        return bool(getattr(self.responder, "dead", False))

    @property
    def answered(self) -> bool:
        """Terminal AND a reply record went out (the done record or a
        typed error) — everything except abandonment, where the vanished
        client was sent nothing.  The fleet leader checkpoints answered
        ids to the board so a takeover coordinator never re-answers a
        request the dead leader already finished."""
        return self._done and self.failed != "abandoned"

    def expired(self, now: float) -> bool:
        return self.deadline_t is not None and now > self.deadline_t

    def _close(self) -> None:
        cb, self._on_close = self._on_close, None
        if cb is not None:
            cb(self)

    def fail(self, error: str, **fields) -> None:
        """Answer the whole request with ONE typed error record and
        retire it (deadline misses, quarantined poison)."""
        if self._done:
            return
        self._done = True
        self.failed = error
        self.responder.send({"id": self.id, "error": error, **fields})
        publish(
            "serve.request.failed",
            id=self.id,
            error=error,
            trace=self.trace_id,
        )
        self._close()

    def abandon(self) -> None:
        """Retire a session whose client vanished: no records (nobody is
        listening), planned rows released, admission cost returned."""
        if self._done:
            return
        self._done = True
        self.failed = "abandoned"
        publish("serve.request.abandoned", id=self.id, trace=self.trace_id)
        self._close()

    def fill(self, j: int, row) -> None:
        """Record sequence ``j``'s (score, n, k) row and emit whatever
        prefix became consecutive."""
        if self._done:
            return
        if self.deadline_t is not None and self._clock.now() > self.deadline_t:
            # Demux-stage deadline checkpoint: the rows landed too late.
            self.fail("deadline")
            return
        self.rows[j] = row
        self._have[j] = True
        self.advance()

    def advance(self) -> None:
        """Emit the longest consecutively-filled prefix; on completion,
        emit the done record and publish the latency event."""
        if self._done:
            return
        n = self.count
        while self._emitted < n and self._have[self._emitted]:
            j = self._emitted
            self.responder.send(
                {
                    "id": self.id,
                    "line": format_result(
                        j,
                        int(self.rows[j][0]),
                        int(self.rows[j][1]),
                        int(self.rows[j][2]),
                    ),
                }
            )
            self._emitted += 1
        if self._emitted == n and not self._done:
            self._done = True
            self.responder.send({"id": self.id, "done": True, "n": n})
            publish(
                "serve.request.done",
                id=self.id,
                n=n,
                latency_s=self._clock.now() - self._admitted_t,
                trace=self.trace_id,
            )
            self._close()


def build_session(item, clock, on_close=None) -> Session:
    """Validate one queued raw request into a :class:`Session`.

    Reuses the batch parser's header validation (same weight-range
    messages as stdin input) plus the encoder's alphabet check and the
    reference buffer caps — the caps must reject HERE, because past this
    point a cap violation would surface as a fatal ``ValueError`` inside
    the scorer and kill the loop.
    """
    raw = item.raw
    rid = raw.get("id")
    rid = f"req-{item.seq}" if rid is None else str(rid)
    deadline_s = raw.get("deadline_s")
    if deadline_s is None:
        deadline_s = env_float("SEQALIGN_SERVE_DEADLINE_S")
    deadline_t = None
    if deadline_s is not None:
        if (
            isinstance(deadline_s, bool)
            or not isinstance(deadline_s, (int, float))
            or deadline_s <= 0
        ):
            raise RequestError(
                f"request {rid!r}: 'deadline_s' must be a positive number"
            )
        # The deadline budget starts at ADMISSION, not at validation:
        # queue wait counts against the SLO.
        deadline_t = item.admitted_t + float(deadline_s)
    weights = raw.get("weights")
    if not isinstance(weights, (list, tuple)) or len(weights) != 4:
        raise RequestError(
            f"request {rid!r}: 'weights' must be a list of 4 integers"
        )
    seq1 = raw.get("seq1")
    if not isinstance(seq1, str) or not seq1.strip():
        raise RequestError(
            f"request {rid!r}: 'seq1' must be a nonempty string"
        )
    seq2 = raw.get("seq2", [])
    if not isinstance(seq2, list) or not all(
        isinstance(s, str) for s in seq2
    ):
        raise RequestError(
            f"request {rid!r}: 'seq2' must be a list of strings"
        )
    try:
        w, s1, _ = _parse_header_tokens(
            [str(x) for x in weights] + [seq1, str(len(seq2))]
        )
        seq1_codes = encode_normalized(s1)
        seq2_codes = [encode_normalized(s) for s in seq2]
    except ValueError as e:
        raise RequestError(f"request {rid!r}: {e}") from None
    if seq1_codes.size > BUF_SIZE_SEQ1:
        raise RequestError(
            f"request {rid!r}: Seq1 length {seq1_codes.size} exceeds "
            f"BUF_SIZE_SEQ1={BUF_SIZE_SEQ1}"
        )
    for j, c in enumerate(seq2_codes):
        if c.size == 0:
            raise RequestError(
                f"request {rid!r}: Seq2[{j}] is empty (whitespace-"
                "delimited batch input cannot express an empty sequence; "
                "drop the entry instead)"
            )
        if c.size > BUF_SIZE_SEQ2:
            raise RequestError(
                f"request {rid!r}: Seq2[{j}] length {c.size} exceeds "
                f"BUF_SIZE_SEQ2={BUF_SIZE_SEQ2}"
            )
    return Session(
        rid, w, s1, seq1_codes, seq2_codes, item.responder,
        item.admitted_t, clock,
        deadline_t=deadline_t,
        cost_s=getattr(item, "cost_s", 0.0),
        on_close=on_close,
        trace_id=getattr(item, "trace_id", ""),
    )


# -- the serve journal -------------------------------------------------------

#: Format fingerprint; foreign --journal files (batch/stream journals,
#: arbitrary JSON) are refused, same stance as utils/journal.py.
SERVE_JOURNAL_FORMAT = "mpi_openmp_cuda_tpu.serve-journal.v1"


def journal_drained(path: str, raw_requests: list[dict]) -> None:
    """Atomically write the drain leftovers: header line, one
    ``{"request": ...}`` record per queued raw dict, and a trailing
    ``{"event": "drain"}`` marker when anything was left.  Whole-file
    tmp+rename (not append): the leftovers ARE the full resume state,
    and a preemption mid-write must leave either the old file or the
    new one, never a torn tail."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(json.dumps({"format": SERVE_JOURNAL_FORMAT}) + "\n")
        for raw in raw_requests:
            f.write(json.dumps({"request": raw}) + "\n")
        if raw_requests:
            f.write(json.dumps({"event": "drain"}) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_drained(path: str) -> list[dict]:
    """Read journaled raw requests back for ``--serve --resume``.

    Missing file → empty (plain ``--journal`` starts fresh; ``--resume``
    asserts existence at the CLI layer first).  A file that parses but
    is not a serve journal raises ``ValueError`` (fatal 65): silently
    rescoring a batch journal's worth of nothing would be worse.  Torn
    or alien trailing records are skipped, the journal reader's
    torn-tail tolerance applied here."""
    if not os.path.exists(path):
        return []
    requests: list[dict] = []
    with open(path, encoding="utf-8") as f:
        head = f.readline()
        if not head.strip():
            return []
        try:
            header = json.loads(head)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"journal {path!r} is not a serve journal (unreadable "
                f"header: {e}); batch/stream/serve journals are mutually "
                "foreign — pass a fresh --journal path"
            ) from None
        if (
            not isinstance(header, dict)
            or header.get("format") != SERVE_JOURNAL_FORMAT
        ):
            raise ValueError(
                f"journal {path!r} is not a serve journal; batch/stream/"
                "serve journals are mutually foreign — pass a fresh "
                "--journal path"
            )
        for line in f:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail: everything before it is intact
            if isinstance(rec, dict) and isinstance(rec.get("request"), dict):
                requests.append(rec["request"])
    return requests
