"""Serve-plane SLO primitives: modelled request cost, token-bucket
admission, and the load-shedding state machine.

The paper's exhaustive ``(len1-len2) x len2`` search makes per-request
cost wildly variable by length, so a binary queue-depth cap either
starves short requests behind long ones or admits an hour of work into
a one-second budget.  Admission here is COST-AWARE: every request is
priced in modelled superblock-wall seconds (``analysis/costmodel``'s
calibrated per-config wall — the same sheet the schedule auditor
prices with), and a token bucket bounds the modelled wall of everything
admitted-but-unfinished.

Determinism contract (seqlint SEQ005, role ``deterministic``): pricing
is pure host arithmetic over the request's lengths; the bucket refills
on *completions*, not on a wall-clock rate, so the same submission
sequence admits and rejects identically on every run.  The only
time-derived inputs are values the serve loop hands in from the
injectable ServeClock it already owns — the queue-wait observations
(:meth:`AdmissionController.observe_wait`) and the per-tick timestamp
(:meth:`AdmissionController.update_state`) — the controller itself
never reads a clock.  Those timestamps feed ONLY the ``retry_after_s``
back-off *hint* (the measured bucket-drain rate); every admit/reject
decision remains clock-free.

The static cost model is an audited prior: ``load/refit.py`` refits it
from measured launch gap rows, and the refit multiplier feeds back in
through ``SEQALIGN_SERVE_COST_SCALE`` (env registry) — prices stay the
modelled wall × one run-constant scale, so determinism is untouched.

Shedding is a three-state machine, escalating one state per serve-loop
tick on the p90 of recent queue waits and de-escalating with
hysteresis::

    accept ----(p90 >= shed_wait_s)----> shed-new ---(p90 >= 4x)---> drain-only
    accept <---(p90 < shed_wait_s/2)---- shed-new <--(p90 < .../2)--

``shed-new`` and ``drain-only`` both reject new admissions with a typed
``overloaded`` error (``retry_after_s`` = the outstanding modelled wall
divided by the *measured* completion-refill rate when one is available
— an honest back-off hint proportional to actual saturation);
``drain-only`` additionally tells the loop to stop gathering (window 0)
so the queue drains at full tilt.
"""

from __future__ import annotations

import collections
import threading

from ..obs.events import publish
from ..obs.metrics import percentile as _percentile
from ..resilience.faults import scheduled as _fault_scheduled
from ..utils.constants import BUF_SIZE_SEQ1, BUF_SIZE_SEQ2
from ..utils.platform import env_float

_BLK = 128

# Shed states, escalation order (the tuple index is the severity).
SHED_ACCEPT = "accept"
SHED_NEW = "shed-new"
SHED_DRAIN = "drain-only"
_SHED_ORDER = (SHED_ACCEPT, SHED_NEW, SHED_DRAIN)

# Queue-wait observations the shed percentile is computed over.
DEFAULT_WAIT_WINDOW = 32

# Per-tick (timestamp, released-total) marks the live bucket-drain
# estimate is computed over: ~DRAIN_WINDOW serve-loop ticks of history.
DRAIN_WINDOW = 16

# The percentile driving shed transitions: one slow straggler must not
# shed, a slow tail must.
_WAIT_PCTL = 0.9


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


# The shed machine's p90 and the report histograms' p50/p90/p99 are the
# SAME rank arithmetic: obs.metrics.percentile is the one implementation
# (imported above as _percentile), so a threshold tuned against report
# percentiles transfers to shedding exactly.


def _best_pair_wall_s(nbn: int, nbi: int) -> float:
    from ..analysis.costmodel import config_cost
    from ..analysis import CostModelError
    from ..ops.pallas_scorer import emittable_superblocks

    best = 0.0
    for sb in emittable_superblocks(nbn, nbi, "i8"):
        try:
            wall = config_cost(nbn, nbi, "i8", sb).model_wall_s
        except CostModelError:
            continue
        if best == 0.0 or wall < best:
            best = wall
    return best


class RequestCostModel:
    """Modelled superblock-wall pricing for admission decisions.

    Per ``(nbn, nbi)`` block-count pair the price is the BEST emittable
    config's modelled wall for one fully-live pair at the i8 feed (the
    serving feed's floor) — a deliberate lower bound: admission must
    never reject work the hardware could actually make in time, so it
    prices optimistically and lets the deadline checkpoints catch the
    rest.  Prices are memoised per block-count pair (the whole space is
    ~24x16 entries), so steady-state pricing is a dict lookup.

    ``scale`` is the measured-load refit multiplier (the load harness's
    closing loop): the modelled wall stays the audited prior, and a
    refit run feeds ``measured/modelled`` back through the env registry
    (``SEQALIGN_SERVE_COST_SCALE``, default 1.0 = trust the prior) so
    the bucket prices in calibrated rather than theoretical seconds.
    Run-constant, so admission stays deterministic per run.
    """

    def __init__(self, *, scale: float | None = None):
        self._pair_wall: dict[tuple[int, int], float] = {}
        if scale is None:
            scale = env_float("SEQALIGN_SERVE_COST_SCALE", 1.0)
        self.scale = max(0.0, float(scale)) or 1.0

    def pair_wall_s(self, len1: int, len2: int) -> float:
        """UNSCALED modelled wall of one pair — the audited prior the
        refit loop measures against."""
        nbn = max(1, _ceil_div(min(int(len1), BUF_SIZE_SEQ1), _BLK))
        nbi = max(1, _ceil_div(min(int(len2), BUF_SIZE_SEQ2), _BLK))
        key = (nbn, nbi)
        wall = self._pair_wall.get(key)
        if wall is None:
            wall = self._pair_wall[key] = _best_pair_wall_s(nbn, nbi)
        return wall

    def request_cost_s(self, raw: dict) -> float:
        """Modelled wall of one raw (still unvalidated) request.
        Defensively prices anything malformed at 0.0 — validation
        rejects it with a typed error on the main thread later; pricing
        runs on reader threads and must never raise."""
        try:
            seq1 = raw.get("seq1")
            seq2 = raw.get("seq2")
            if not isinstance(seq1, str) or not isinstance(seq2, list):
                return 0.0
            total = 0.0
            for s in seq2:
                if isinstance(s, str) and s:
                    total += self.pair_wall_s(len(seq1), len(s))
            return total * self.scale
        except Exception:
            # advisory: admission cost estimate only — 0.0 admits the
            # request and the scorer's own contracts still gate it.
            return 0.0


class AdmissionController:
    """Token-bucket admission + the accept/shed-new/drain-only machine.

    Thread contract: :meth:`admit` runs on reader threads (under the
    queue's condition, which never re-enters here), :meth:`release` on
    whichever thread retires a session, and :meth:`update_state` on the
    serve loop's main thread once per tick; every mutation is guarded
    by the controller's own lock (seqlint SEQ008), and the controller
    never calls back into the queue, so the queue->controller lock
    order is acyclic.
    """

    def __init__(
        self,
        *,
        budget_s: float,
        shed_wait_s: float,
        cost_model: RequestCostModel | None = None,
        wait_window: int = DEFAULT_WAIT_WINDOW,
    ):
        if budget_s <= 0:
            raise ValueError(f"admission budget_s must be > 0, got {budget_s}")
        if shed_wait_s <= 0:
            raise ValueError(
                f"shed_wait_s threshold must be > 0, got {shed_wait_s}"
            )
        self.budget_s = float(budget_s)
        self.shed_wait_s = float(shed_wait_s)
        self.cost_model = cost_model or RequestCostModel()
        self._lock = threading.Lock()
        self._outstanding_s = 0.0
        self._state = SHED_ACCEPT
        self._waits: collections.deque[float] = collections.deque(
            maxlen=max(1, int(wait_window))
        )
        # Live drain estimate: lifetime released cost + per-tick
        # (loop timestamp, released total) marks.  The timestamps are
        # handed IN by the loop (update_state(now=...)) — never read
        # here — and feed only the retry_after_s hint, not decisions.
        self._released_total_s = 0.0
        self._drain_marks: collections.deque[tuple[float, float]] = (
            collections.deque(maxlen=DRAIN_WINDOW)
        )

    @property
    def state(self) -> str:
        return self._state

    def outstanding_s(self) -> float:
        return self._outstanding_s

    def drain_rate(self) -> float:
        """Measured completion-refill rate: modelled-cost seconds
        released per wall second over the recent tick window (0.0 until
        two ticks with completions between them have been observed)."""
        with self._lock:
            return self._drain_rate_locked()

    def _drain_rate_locked(self) -> float:
        if len(self._drain_marks) < 2:
            return 0.0
        t0, r0 = self._drain_marks[0]
        t1, r1 = self._drain_marks[-1]
        if t1 <= t0 or r1 <= r0:
            return 0.0
        return (r1 - r0) / (t1 - t0)

    def retry_after_s(self) -> float:
        """Client back-off hint: the wall seconds until the outstanding
        work drains at the MEASURED completion-refill rate (the live
        token-bucket drain estimate) — so back-off is proportional to
        actual saturation, not the cost model's optimism.  Before any
        drain has been measured (cold start, first overload tick) it
        falls back to the static prior — the modelled wall of the
        outstanding work itself — and is floored so a zero-cost
        rejection still backs off."""
        with self._lock:
            outstanding = self._outstanding_s
            rate = self._drain_rate_locked()
        hint = outstanding / rate if rate > 0.0 else outstanding
        return round(max(0.05, hint), 3)

    def admit(self, raw: dict) -> tuple[str | None, float]:
        """Price one raw request and charge the bucket.  Returns
        ``(rejection, cost_s)``; rejection is None when admitted (the
        cost is charged and the caller owes exactly one
        :meth:`release`), else the shed reason."""
        cost = self.cost_model.request_cost_s(raw)
        if _fault_scheduled("overload-burst"):
            # Chaos marker: this request arrives as part of a modelled
            # burst that exhausts the bucket on its own.
            cost = cost + self.budget_s + 1.0
        if _fault_scheduled("burst:overload"):
            # Chaos marker: sustained open-loop overload — this request
            # arrives priced at 5x its modelled wall, the saturation
            # regime the load harness drives for real.
            cost = cost * 5.0
        with self._lock:
            if self._state != SHED_ACCEPT:
                return self._state, cost
            if (
                self._outstanding_s > 0.0
                and self._outstanding_s + cost > self.budget_s
            ):
                # An over-budget request against an EMPTY bucket is
                # still admitted: no completion could ever make it fit,
                # so rejecting would reject it forever — the deadline
                # checkpoints are what catch impossible requests.
                return "overloaded", cost
            self._outstanding_s += cost
            return None, cost

    def release(self, cost_s: float) -> None:
        """Return one admitted request's tokens (request done, failed,
        abandoned, or rejected at validation)."""
        with self._lock:
            self._outstanding_s = max(0.0, self._outstanding_s - cost_s)
            self._released_total_s += max(0.0, float(cost_s))

    def observe_wait(self, wait_s: float) -> None:
        """One popped request's queue wait (admission to pop)."""
        with self._lock:
            self._waits.append(float(wait_s))

    def note_idle(self) -> None:
        """Serve-loop signal: the queue is empty this tick, so the next
        arrival would wait ~nothing — feed a zero observation so the
        percentile decays and shed states can step back down."""
        with self._lock:
            self._waits.append(0.0)

    def update_state(self, now: float | None = None) -> str:
        """One tick's shed transition (main loop thread only): move at
        most one state toward where the wait percentile points.

        ``now`` is the loop's ServeClock timestamp for this tick; it
        marks the drain-rate window for :meth:`retry_after_s` and
        touches no transition decision (those stay clock-free)."""
        with self._lock:
            if now is not None:
                self._drain_marks.append(
                    (float(now), self._released_total_s)
                )
            p = _percentile(self._waits, _WAIT_PCTL)
            cur = _SHED_ORDER.index(self._state)
            if p >= 4.0 * self.shed_wait_s:
                target = 2
            elif p >= self.shed_wait_s:
                target = max(cur, 1)
            elif p < 0.5 * self.shed_wait_s:
                target = 0
            else:
                # Hysteresis band: hold the current state.
                target = cur
            if target == cur:
                return self._state
            nxt = cur + (1 if target > cur else -1)
            self._state = _SHED_ORDER[nxt]
            state = self._state
        publish("serve.shed.state", state=state, p90=round(p, 6))
        return state
