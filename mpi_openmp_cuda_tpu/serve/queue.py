"""Request queue with deterministic admission control.

Reader threads (socket connections, the stdin ingest) call
:meth:`RequestQueue.submit`; the main serve loop calls
:meth:`RequestQueue.pop_ready`.  Admission is **deterministic**: the
decision inputs are the current queue depth against ``max_depth`` and
the admission controller's token bucket of modelled superblock-wall
cost (:mod:`.slo` — pure host arithmetic over the request's lengths,
refilled by completions, never a clock or a measured rate) — so the
same submission sequence with the same completion order admits and
rejects identically (this file is on seqlint SEQ005's
deterministic-path list, like ``resilience/``).  The admit *timestamp*
is recorded (for the latency histogram and the shed-state wait
percentiles) but never decides a single admission.

Requests are held as RAW parsed dicts: full validation (weights range,
sequence alphabet, buffer caps) happens on the main loop thread in
:mod:`.session`, where the span recorder lives — reader threads only
``json.loads`` and enqueue, keeping the single-threaded-spans contract
of :mod:`..obs.spans`.

``pop_ready`` is the continuous-batching seam: it waits (via the
injectable :class:`..serve.clock.ServeClock`) for at least one queued
request, then lingers one *gather window* so a concurrent burst
coalesces into a single superblock plan instead of one dispatch per
request.  The window is skipped when every input source has closed —
nothing more can arrive, so waiting only adds latency.
"""

from __future__ import annotations

import dataclasses
import threading

from ..obs.events import publish

#: Admission verdicts (strings so responders can embed them in errors).
ADMIT_OK = "ok"
ADMIT_FULL = "full"
ADMIT_CLOSED = "closed"
ADMIT_OVERLOADED = "overloaded"


@dataclasses.dataclass
class QueuedRequest:
    """One admitted raw request awaiting the loop: the unvalidated dict,
    the responder that owns its result lines, the admit time (histogram
    input only), a process-unique sequence number (the default request
    id), and the modelled wall charged against the admission bucket
    (released when the session retires)."""

    raw: dict
    responder: object
    admitted_t: float
    seq: int
    cost_s: float = 0.0
    # Per-request trace id, minted at admission from the queue's own
    # sequence counter (deterministic — no clock, SEQ005) and carried
    # on every bus event this request causes (obs/trace.py).
    trace_id: str = ""


class RequestQueue:
    """Bounded FIFO of :class:`QueuedRequest` under one condition.

    ``max_depth`` is the backpressure contract: a submit past it is
    rejected with :data:`ADMIT_FULL` (the client resubmits) instead of
    growing the queue without bound.  ``close()`` stops admission for
    the drain; ``drain_pending()`` hands the leftovers to the journal.
    """

    def __init__(self, max_depth: int, clock, controller=None):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = int(max_depth)
        self._clock = clock
        # Optional slo.AdmissionController; lock order is strictly
        # queue -> controller (the controller never calls back here).
        self._controller = controller
        self._cond = threading.Condition()
        self._items: list[QueuedRequest] = []
        self._closed = False
        self._sources = 0
        self._seq = 0

    # -- source bookkeeping ------------------------------------------------

    def open_source(self) -> None:
        """A producer (socket listener, stdin ingest) came up."""
        with self._cond:
            self._sources += 1

    def close_source(self) -> None:
        """A producer finished; with zero sources and an empty queue the
        loop knows the run is complete (stdin/file mode)."""
        with self._cond:
            self._sources = max(0, self._sources - 1)
            self._cond.notify_all()

    # -- admission ---------------------------------------------------------

    def submit(self, raw: dict, responder) -> str:
        """Admit one raw request; returns an ADMIT_* verdict.

        The bus event is published AFTER ``_cond`` is released: publish
        fans out synchronously to the obs recorders (each behind its own
        lock, the flight recorder with file I/O on trigger events), so
        publishing under the queue condition would nest every recorder
        lock — and a dump's disk write — beneath the serve lock every
        reader thread contends (analysis/lockgraph.py rule b)."""
        with self._cond:
            rejection = None
            cost = 0.0
            if not self._closed and self._controller is not None:
                rejection, cost = self._controller.admit(raw)
            if self._closed:
                verdict, event, fields = ADMIT_CLOSED, "serve.request.rejected", {
                    "reason": "closed", "depth": len(self._items),
                }
            elif rejection is not None:
                verdict, event, fields = ADMIT_OVERLOADED, "serve.request.shed", {
                    "reason": rejection, "depth": len(self._items),
                }
            elif len(self._items) >= self.max_depth:
                if self._controller is not None:
                    # The bucket admitted it; the depth backstop did not.
                    self._controller.release(cost)
                verdict, event, fields = ADMIT_FULL, "serve.request.rejected", {
                    "reason": "full", "depth": len(self._items),
                }
            else:
                self._seq += 1
                trace_id = f"t{self._seq}"
                rid = raw.get("id")
                self._items.append(
                    QueuedRequest(
                        raw,
                        responder,
                        self._clock.now(),
                        self._seq,
                        cost,
                        trace_id,
                    )
                )
                self._cond.notify_all()
                verdict, event, fields = ADMIT_OK, "serve.request.admitted", {
                    "depth": len(self._items),
                    "id": f"req-{self._seq}" if rid is None else str(rid),
                    "trace": trace_id,
                }
        publish(event, **fields)
        return verdict

    def close(self) -> None:
        """Stop admission (drain); waiters wake immediately."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- the loop side -----------------------------------------------------

    def depth(self) -> int:
        with self._cond:
            return len(self._items)

    def idle(self) -> bool:
        """Nothing queued and no producer left to queue more."""
        with self._cond:
            return not self._items and self._sources == 0

    def pop_ready(
        self,
        timeout_s: float,
        window_s: float,
        limit: int = 0,
        wake=None,
    ) -> list[QueuedRequest]:
        """Pop up to ``limit`` requests (0 = all), coalescing a burst.

        Phase 1 waits up to ``timeout_s`` for work (or ``wake()``, the
        drain flag: the wait is bounded so a signal is noticed within
        one tick).  Phase 2 lingers ``window_s`` with work in hand while
        sources are still open, so concurrently-arriving requests land
        in the SAME pop — that is what turns per-request dispatches into
        shared superblocks.
        """

        def wake_up() -> bool:
            return bool(wake is not None and wake())

        with self._cond:
            self._clock.block_until(
                self._cond,
                lambda: bool(self._items)
                or self._closed
                or self._sources == 0
                or wake_up(),
                timeout_s,
            )
            if self._items and self._sources > 0 and not wake_up():
                self._clock.block_until(
                    self._cond,
                    lambda: self._closed
                    or wake_up()
                    or (0 < limit <= len(self._items)),
                    window_s,
                )
            take = len(self._items) if limit <= 0 else min(limit, len(self._items))
            popped, self._items[:take] = self._items[:take], []
            return popped

    def snapshot_raws(self) -> list[dict]:
        """Copy of the queued raw dicts in admission order, WITHOUT
        popping (the serve loop's live journal rewrite — the queue keeps
        ownership of every item)."""
        with self._cond:
            return [it.raw for it in self._items]

    def drain_pending(self) -> list[QueuedRequest]:
        """Remove and return everything still queued (drain journaling)."""
        with self._cond:
            popped, self._items[:] = list(self._items), []
            return popped
