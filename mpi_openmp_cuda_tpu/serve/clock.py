"""The injectable serve-plane clock — the ONE home for blocking waits.

Everything under ``serve/`` that needs "now" or "wait until" goes
through a :class:`ServeClock` instance handed in at construction, for
two reasons:

* **Determinism**: tests inject a fake clock whose ``block_until``
  returns immediately, so admission/coalescing behaviour is exercised
  without real sleeps (the same stance as the metrics registry's
  injectable clock and the retry policy's seeded backoff).
* **Drain responsiveness**: every wait is a *bounded, condition-based*
  wait — a bare ``time.sleep`` or raw ``Condition.wait`` sprinkled
  through the loop would add un-interruptible latency between a SIGTERM
  and the drain's exit 75.

seqlint SEQ007 enforces this: ``time.sleep`` and ``.wait``/
``.wait_for`` calls anywhere else under ``serve/`` are violations;
this module is the single exemption.
"""

from __future__ import annotations

import time


class ServeClock:
    """Monotonic now + bounded condition wait, both injectable.

    ``block_until`` must be called with ``cond``'s lock held (the
    ``threading.Condition.wait_for`` contract); it returns the
    predicate's final value so callers can distinguish "woke because
    true" from "woke on timeout".
    """

    def __init__(self, now=time.monotonic):
        self._now = now

    def now(self) -> float:
        return self._now()

    def block_until(self, cond, predicate, timeout_s: float) -> bool:
        """Wait on ``cond`` until ``predicate()`` or ``timeout_s``."""
        return cond.wait_for(predicate, timeout=timeout_s)
