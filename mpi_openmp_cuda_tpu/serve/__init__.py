"""Serving plane: a persistent alignment server with bucketed
continuous batching (ROADMAP Open item 1; docs/ARCHITECTURE.md §12).

The reference is a one-shot stdin→stdout batch binary; this package
turns the PR-1/4/5 substrate (retry policy, SIGTERM drain with
resumable exit 75, heartbeats, run-report metrics) into SLO machinery:

* :mod:`.clock` — the injectable serve clock, the ONE legal home for
  blocking waits under ``serve/`` (seqlint SEQ007);
* :mod:`.queue` — deterministic admission control over raw request
  dicts (SEQ005-clean: no wall-clock reads, decisions are depth-based);
* :mod:`.session` — per-request lifecycle: typed validation, ordered
  result emission, done/error records, the serve journal;
* :mod:`.batcher` — continuous batching: Seq2 rows from CONCURRENT
  requests coalesce into shared fixed-shape superblocks on the existing
  length-bucket schedule, tagged for demux;
* :mod:`.loop` — the serve loop itself: warm jit caches across
  requests, dispatch through the unchanged ``AlignmentScorer`` via the
  shared :mod:`..io.pipeline`, drain → journal → exit 75.

Imports stay lazy at the CLI boundary (``--serve`` goes through
``_feature_import``), so batch runs never pay for the server.
"""
