"""Elastic serve fleet: coordinator-side dispatch + scoring workers.

The serve loop (serve/loop.py) stays the **coordinator** — admission,
SLO armor, ``plan_blocks``, demux, journaling are unchanged — but with
``--fleet-board DIR`` armed, planned superblocks are *offered* on a
:class:`~..resilience.rescue.FileBoard` instead of scored in-process.
N ``--fleet-worker`` processes register on the same board, heartbeat,
claim offers under expiring leases, score them through the shared
:class:`~..io.pipeline.ChunkPipeline` (same retry/degrade ladder as
everywhere else), and post epoch-stamped results.

The failure model (docs/ARCHITECTURE.md §8.6):

* a worker that dies mid-superblock (SIGKILL) stops heartbeating; the
  coordinator's membership deadline declares it dead and re-dispatches
  its held superblocks to a survivor;
* a worker that stalls (claims, never posts) hits the lease deadline —
  same re-dispatch, no death verdict needed;
* a **zombie** (declared dead but still running) may post its result
  late: the post carries the OLD lease epoch, the coordinator fences it
  (counted, never demuxed), so no request is ever double-answered;
* a torn result post reads as missing (resilience/membership.py), so
  the lease expires and the block is re-dispatched;
* with NO live workers, every block — new or orphaned — scores locally
  on the coordinator through the PR-1 degrade chain.  The fleet is an
  accelerator, never an availability dependency;
* a superblock whose lease keeps expiring does not re-offer forever:
  the fencing epoch doubles as the attempt counter, and past
  ``SEQALIGN_FLEET_MAX_REDISPATCH`` bumps the block takes the typed
  **dead-letter** path — scored locally through the serve loop's
  quarantine ladder (retry → degrade → poison bisection), so a
  poisoned request is *answered* (``{"id", "error": "poisoned"}``),
  never orbited.

**Coordinator failover** (PR 16) extends the same model one layer up.
The coordinator holds a :class:`~..resilience.membership.LeaderLease`:
it claims a fleet **generation** at startup, renews a beat on every
pump tick, stamps its generation into every block id (``g<gen>b<seq>``),
and checkpoints its unanswered requests + answered reply ids to the
board.  A ``--fleet-standby`` process (:func:`standby_wait`) watches
the newest generation's beat with the worker-heartbeat staleness rule;
when the leader goes silent, the standby claims the next generation,
replays the checkpoint, and re-answers only what was never answered —
exactly-once across ``kill -9`` at tick boundaries.  A deposed leader
(one that observes a higher generation) raises
:class:`LeadershipLostError` on its next pump *before* collecting or
demuxing anything, and its late board posts are fenced by generation —
counted by the new leader's board GC, never read.  The GC also keeps
the board bounded: retired-epoch debris and dead generations' keys are
swept each tick past a grace window.
"""

from __future__ import annotations

import collections
import json
import math
import os
import re
import threading

import numpy as np

from ..obs.events import log_line, publish
from ..obs.export import collect_worker_snapshot, post_worker_snapshot
from ..obs.flightrec import dump_fleet_tape
from ..obs.metrics import active_metrics
from ..obs.metrics import gauge as obs_gauge
from ..obs.spans import span
from ..obs.trace import (
    active_trace,
    trace_board_phase,
    trace_clock_offsets,
)
from ..resilience.drain import drain_requested
from ..resilience.faults import fire as _fault_fire
from ..resilience.faults import scheduled as _fault_scheduled
from ..resilience.membership import (
    FLEET_PREFIX,
    OFFER_PREFIX,
    ClockOffsetEstimator,
    LeaseTable,
    Membership,
    board_read_json,
    ckpt_key,
    claim_key,
    heartbeat_key,
    obs_snapshot_key,
    offer_key,
    result_key,
    shutdown_key,
    worker_key,
)
from ..utils.platform import env_float, env_int
from .clock import ServeClock

#: Coordinator board-poll cadence: one membership/lease tick per poll.
_POLL_S = 0.05

#: Coordinator obs-gather cadence, in pump ticks: how often live
#: workers' posted observability snapshots are folded into the local
#: registry/tracer.  Snapshots overwrite in place on the board, so a
#: slow gather loses granularity, never correctness.
_OBS_GATHER_TICKS = 5


def lease_ticks_for(lease_s=None, poll_s=_POLL_S) -> int:
    """The one lease-window formula, shared by the coordinator's worker
    leases and the standby's leader-watch deadline — a takeover must
    land within the same window a worker death verdict does."""
    if lease_s is None:
        lease_s = env_float("SEQALIGN_LEASE_S", 2.0)
    return max(2, round(float(lease_s) / float(poll_s)))


def _gen_of(name: str) -> int | None:
    """Parse a ``g<gen>`` key segment (leader/leaderhb/ckpt names)."""
    if not name.startswith("g"):
        return None
    try:
        return int(name[1:])
    except ValueError:
        return None


def _epoch_of(name: str) -> int | None:
    """Parse an ``e<epoch>`` key segment (claim/result leaf names)."""
    if not name.startswith("e"):
        return None
    try:
        return int(name[1:])
    except ValueError:
        return None


def _pause(clock, seconds: float, predicate=None) -> None:
    """Bounded wait through the injectable clock (SEQ007: the ServeClock
    is the one legal wait seam).  A fresh local Condition per wait —
    nothing ever notifies it, the timeout is the only wake-up, which is
    exactly what a board poll interval needs."""
    cond = threading.Condition()
    with cond:
        clock.block_until(cond, predicate or (lambda: False), seconds)


def _block_traces(block) -> list[str]:
    """The admission-minted trace ids riding a superblock (empty for
    blocks built without tags — unit-test stubs, replayed journals)."""
    fn = getattr(block, "link_traces", None)
    return [str(t) for t in (fn() if fn is not None else ())]


def _block_links(block) -> list[str]:
    """The request ids riding a superblock (same stance as above)."""
    fn = getattr(block, "link_ids", None)
    return [str(r) for r in (fn() if fn is not None else ())]


def _offer_traces(offer: dict) -> list[str]:
    """The trace ids an offer propagated (empty for old-protocol or
    hand-crafted offers — the worker still scores them)."""
    return [str(t) for t in (offer.get("traces") or ())]


def _finite(x) -> float:
    """Coerce one phase delta to a finite float (0.0 for anything
    else) — the board-phase gate requires every row finite."""
    try:
        v = float(x)
    except (TypeError, ValueError):
        return 0.0
    return v if math.isfinite(v) else 0.0


class LeadershipLostError(RuntimeError):
    """This coordinator observed a higher leader generation: a standby
    took over.  The deposed leader must stop — answering anything after
    this point could double a reply the successor is about to give.
    Raised from ``pump()`` before any collect/demux, so the answer
    window of a zombie leader is bounded by one board poll."""


class FleetCoordinator:
    """Coordinator-side fleet state: the membership view, the lease
    table, offer/result board traffic, and the re-dispatch policy.

    Driven entirely from the serve loop's main thread — ``offer()`` at
    dispatch, ``pump()`` once per loop tick — so there is no shared
    mutable state and no locking.  Every decision is tick-counted: one
    ``pump`` that actually polls the board is one tick for membership
    deadlines and lease expiry alike.
    """

    #: Retired blocks kept under the stale-result probe, so a zombie's
    #: late post is still *counted* as fenced after its block finished.
    _RETIRED_PROBE = 64

    def __init__(
        self,
        board,
        *,
        local_score,
        demux,
        clock=None,
        lease_s=None,
        poll_s=_POLL_S,
        leader=None,
        max_redispatch=None,
    ):
        self.board = board
        self.clock = clock or ServeClock()
        self._local_score = local_score
        self._demux = demux
        self.poll_s = float(poll_s)
        self.lease_ticks = lease_ticks_for(lease_s, self.poll_s)
        self.membership = Membership(board, deadline_ticks=self.lease_ticks)
        self.leases = LeaseTable(self.lease_ticks)
        self.expected = env_int("SEQALIGN_FLEET_WORKERS", 0)
        self._full_logged = False
        self.blocks: dict = {}  # bid -> SuperBlock (tags stay local)
        self._seq = 0
        self._tick = 0
        self._last_poll = None
        self._fenced_seen: set[str] = set()
        self._retired = collections.deque(maxlen=self._RETIRED_PROBE)
        # Failover state (PR 16).  ``leader`` is the held LeaderLease, or
        # None for a leaderless coordinator (unit tests, the in-memory
        # interleave scenarios) — which behaves as generation 0 with no
        # beat, no deposition, and no checkpointing.
        self.leader = leader
        self.gen = (
            leader.gen if leader is not None and leader.gen is not None else 0
        )
        if max_redispatch is None:
            max_redispatch = env_int("SEQALIGN_FLEET_MAX_REDISPATCH", 5)
        self.max_redispatch = int(max_redispatch)
        self.gc_ticks = (
            env_int("SEQALIGN_FLEET_GC_TICKS", 0) or 2 * self.lease_ticks
        )
        self._deposed = False
        self._zombie_leader = False  # chaos: freeze the beat, earn deposition
        self._gc_marks: dict[str, int] = {}  # sweepable key -> tick marked
        self._gc_fenced: set[str] = set()  # stale-gen keys already counted
        self._ckpt_blob: str | None = None  # change-cache for checkpoint()
        # Fleet observability plane (this PR): deterministic per-worker
        # clock offsets from offer/claim echo pairs, per-block phase
        # timestamps (overwritten on re-offer — the phase row describes
        # the attempt that actually finished), and the dead workers
        # whose flight-recorder tape was already collected.
        self.offsets = ClockOffsetEstimator()
        self._phase_marks: dict[str, dict] = {}
        self._tapes_collected: set[str] = set()

    # -- dispatch side -----------------------------------------------------

    def accepting(self) -> bool:
        """Offers only make sense with a live worker to claim them; the
        serve loop scores locally otherwise."""
        return self.membership.live_count() > 0

    def outstanding(self) -> int:
        return len(self.blocks)

    def offer(self, block) -> str:
        """Put one planned superblock on the board under a fresh lease.
        Only the scoring payload crosses the board — session tags (live
        object references) stay coordinator-side, keyed by block id.

        Block ids are generation-scoped (``g<gen>b<seq>``): a successor
        leader restarting its sequence at 1 must never collide with the
        dead leader's keys — those are fenced debris, not its namespace.

        The post happens BEFORE any lease state exists: on a board that
        cannot take the write (ENOSPC), the raised OSError propagates to
        the dispatcher with nothing to unwind, and the serve loop's
        quarantine ladder scores the block instead.
        """
        bid = f"g{self.gen}b{self._seq + 1}"
        self._post_offer(bid, 0, block)  # a fresh lease starts at epoch 0
        self._seq += 1
        self.blocks[bid] = block
        self.leases.issue(bid, self._tick)
        return bid

    def _post_offer(self, bid: str, epoch: int, block) -> None:
        """The offer is a WORK UNIT crossing a process boundary, so it
        carries its trace context (seqlint SEQ015): the admission-minted
        trace ids and request ids riding this superblock, plus the
        coordinator-clock post time — the first half of the offer/claim
        echo pair the clock-offset estimator feeds on."""
        t_offer = float(self.clock.now())
        self.board.post(
            offer_key(bid),
            json.dumps({
                "bid": bid,
                "epoch": int(epoch),
                "weights": [int(w) for w in block.weights],
                "seq1": np.asarray(block.seq1_codes).tolist(),
                "rows": [np.asarray(c).tolist() for c in block.codes],
                "traces": _block_traces(block),
                "links": _block_links(block),
                "t_offer": t_offer,
            }),
        )
        self._phase_marks[bid] = {"epoch": int(epoch), "t_offer": t_offer}

    # -- the per-tick pump -------------------------------------------------

    def pump(self, idle: bool = False) -> None:
        """One serve-loop tick's worth of fleet work: poll the board at
        most once per ``poll_s`` — membership observe, stale-post
        fencing, result collection, lease expiry → re-dispatch.  When
        the loop is otherwise idle with blocks in flight, sleep out the
        remainder of the poll interval instead of spinning."""
        now = self.clock.now()
        if self._last_poll is not None:
            wait = self.poll_s - (now - self._last_poll)
            if wait > 0:
                if not (idle and self.blocks):
                    return
                _pause(self.clock, wait, drain_requested)
        self._last_poll = self.clock.now()
        self._tick += 1
        tick = self._tick
        # kill:fleet-coordinator rides this fire point: SIGKILL at the
        # pump-tick boundary, after the previous tick's checkpoint
        # landed — the standby-takeover chaos tier.
        _fault_fire("fleet_pump")
        if self.leader is not None:
            if _fault_scheduled("zombie:fleet-leader"):
                self._zombie_leader = True
                log_line(
                    "mpi_openmp_cuda_tpu: fleet: leader "
                    f"gen {self.gen} going zombie — beat frozen (chaos)"
                )
            # Deposition check FIRST, before renew and before any
            # collect/demux: a zombie leader's answer window is one poll.
            if self.leader.deposed():
                self._deposed = True
                publish(
                    "leader.deposed", gen=self.gen, leader=self.leader.lid
                )
                log_line(
                    f"mpi_openmp_cuda_tpu: fleet: leader gen {self.gen} "
                    "deposed by a higher generation; stopping"
                )
                raise LeadershipLostError(
                    f"fleet leader generation {self.gen} was superseded"
                )
            if not self._zombie_leader:
                self.leader.renew()
        joined, died = self.membership.observe(tick)
        for wid in joined:
            log_line(
                f"mpi_openmp_cuda_tpu: fleet: worker {wid} joined "
                f"({self.membership.live_count()} live)"
            )
        if (
            not self._full_logged
            and self.expected
            and self.membership.live_count() >= self.expected
        ):
            self._full_logged = True
            log_line(
                "mpi_openmp_cuda_tpu: fleet: complete "
                f"({self.expected} worker(s) registered)"
            )
        for wid in died:
            log_line(
                f"mpi_openmp_cuda_tpu: fleet: worker {wid} missed its "
                "heartbeat deadline; re-dispatching its superblocks"
            )
            # Tape first, re-dispatch second: the dead worker's last
            # posted snapshot is the only record of what it was doing.
            self._collect_tape(wid)
            for lease in self.membership_held(wid):
                self._redispatch(lease.bid, "worker-dead")
        for bid in list(self.blocks):
            self._collect(bid, tick)
        self._probe_retired()
        for lease in self.leases.expired(tick):
            if lease.bid not in self.blocks:
                continue
            publish(
                "lease.expired",
                block=lease.bid,
                epoch=lease.epoch,
                worker=lease.holder,
            )
            log_line(
                f"mpi_openmp_cuda_tpu: fleet: lease on {lease.bid} "
                f"(epoch {lease.epoch}, holder {lease.holder}) expired; "
                "re-dispatching"
            )
            self._redispatch(lease.bid, "lease-expired")
        self._gc(tick)
        if tick % _OBS_GATHER_TICKS == 0:
            self._gather_obs()
        obs_gauge("fleet_workers", self.membership.live_count())

    def membership_held(self, wid: str):
        return [
            lease for lease in self.leases.held_by(wid)
            if lease.bid in self.blocks
        ]

    def _collect(self, bid: str, tick: int) -> None:
        lease = self.leases.get(bid)
        block = self.blocks[bid]
        self._fence_stale(bid, lease.epoch)
        post = board_read_json(self.board, result_key(bid, lease.epoch))
        if post is not None:
            rows = self._valid_rows(post, bid, len(block.codes))
            if rows is not None:
                self.blocks.pop(bid)
                self.leases.retire(bid)
                self._retired.append((bid, int(post["epoch"])))
                self.board.delete(offer_key(bid))
                self._demux(rows, block)
                self._note_phases(bid, post, block)
                return
        if lease.holder is None:
            claim = board_read_json(
                self.board, claim_key(bid, lease.epoch)
            )
            if claim is not None and claim.get("wid"):
                wid = str(claim["wid"])
                self.leases.note_claim(bid, wid, tick)
                self._note_claim_echo(bid, wid, claim)

    def _fence_stale(self, bid: str, current: int) -> None:
        """Probe every PREVIOUS epoch's result key: a post there is a
        zombie's late answer — observed once (event + counter), never
        demuxed.  Exactly-once holds structurally (the demux only ever
        reads the current-epoch key); this makes the fencing visible."""
        for epoch in range(int(current)):
            key = result_key(bid, epoch)
            if key in self._fenced_seen:
                continue
            if self.board.get(key) is None:
                continue
            self._fenced_seen.add(key)
            post = board_read_json(self.board, key) or {}
            publish(
                "lease.fenced",
                block=bid,
                epoch=epoch,
                current=int(current),
                worker=post.get("wid"),
            )
            log_line(
                f"mpi_openmp_cuda_tpu: fleet: fenced stale epoch-{epoch} "
                f"result for {bid} (current epoch {int(current)})"
            )

    def _probe_retired(self) -> None:
        for bid, final_epoch in self._retired:
            self._fence_stale(bid, final_epoch)

    def _valid_rows(self, post: dict, bid: str, n_rows: int):
        """Accept a result post only if it carries the CURRENT lease
        epoch (the fencing predicate) and well-shaped rows.  Anything
        else reads as missing — the lease deadline re-dispatches."""
        try:
            epoch = int(post.get("epoch", -1))
        except (TypeError, ValueError):
            return None
        if not self.leases.admits(bid, epoch):
            return None
        try:
            rows = np.asarray(post.get("rows"), dtype=np.int64)
        except (TypeError, ValueError):
            return None
        if rows.shape != (int(n_rows), 3):
            return None
        return rows

    # -- fleet observability: clock offsets, board phases, gather ----------

    def _note_claim_echo(self, bid: str, wid: str, claim: dict) -> None:
        """Feed the offer/claim echo pair to the clock-offset estimator
        (NTP-style midpoint: the worker's ``t_claim`` echo against this
        clock's post/seen bracket) and remember the claim times for the
        block's eventual phase row.  Old-protocol claims without the
        echo simply contribute nothing — absence over negotiation."""
        marks = self._phase_marks.get(bid)
        if marks is None or "t_claim" not in claim:
            return
        t_seen = float(self.clock.now())
        self.offsets.observe(wid, marks["t_offer"], claim["t_claim"], t_seen)
        marks["wid"] = wid
        marks["t_claim_w"] = claim["t_claim"]
        marks["t_claim_seen"] = t_seen
        trace_clock_offsets(self.offsets.snapshot())

    def _note_phases(self, bid: str, post: dict, block) -> None:
        """One demuxed fleet superblock → one five-phase breakdown row
        on the trace plane (offer-posted → claimed → score-started →
        result-posted → demuxed).  Worker-stamped times are mapped onto
        this clock through the estimated offset; worker-to-worker
        deltas need no mapping (the offset cancels).  Every delta is
        clamped finite and non-negative, and ``total`` is the SUM of
        the four intervals — totals==sums holds by construction."""
        marks = self._phase_marks.pop(bid, None)
        if marks is None:
            return
        wid = str(post.get("wid") or marks.get("wid") or "")
        t_demux = float(self.clock.now())
        off = self.offsets.offset(wid)

        def to_local(t_worker, fallback):
            mapped = (
                self.offsets.to_coordinator(wid, t_worker)
                if t_worker is not None
                else None
            )
            return mapped if mapped is not None else fallback

        t_offer = float(marks["t_offer"])
        t_claim = to_local(
            marks.get("t_claim_w"), marks.get("t_claim_seen", t_offer)
        )
        t_score = to_local(post.get("t_score"), t_claim)
        t_post = to_local(post.get("t_post"), t_score)
        phases = {
            "offer_to_claim": max(0.0, _finite(t_claim - t_offer)),
            "claim_to_score": max(0.0, _finite(t_score - t_claim)),
            "score_to_post": max(0.0, _finite(t_post - t_score)),
            "post_to_demux": max(0.0, _finite(t_demux - t_post)),
        }
        phases = {k: round(v, 9) for k, v in phases.items()}
        phases["total"] = round(sum(phases.values()), 9)
        trace_board_phase({
            "bid": bid,
            "worker": wid,
            "epoch": int(marks.get("epoch", 0)),
            "traces": _block_traces(block),
            "request_ids": _block_links(block),
            "clock_offset_s": round(off, 9) if off is not None else None,
            "phases": phases,
        })

    def _gather_obs(self) -> None:
        """Fold live workers' posted observability snapshots into the
        local planes: metrics into the registry's fleet section (the
        federated ``/metrics`` families), trace events into offset-
        aligned per-worker Perfetto tracks.  Best-effort per worker —
        a missing, torn, or alien snapshot contributes nothing."""
        reg = active_metrics()
        tracer = active_trace()
        if reg is None and tracer is None:
            return
        for wid, view in list(self.membership.workers.items()):
            if not view.alive:
                continue
            snap = collect_worker_snapshot(self.board, wid)
            if snap is None:
                continue
            if reg is not None and isinstance(snap.get("metrics"), dict):
                reg.record_fleet(wid, snap["metrics"])
            if tracer is not None:
                self._merge_track(tracer, wid, snap)

    def _merge_track(self, tracer, wid: str, snap: dict) -> None:
        """Install one worker's trace events as a per-worker track,
        shifted onto this tracer's timeline: worker trace-clock →
        worker board-clock (the snapshot's back-to-back bridge pair) →
        coordinator board-clock (the offer/claim offset estimate) →
        coordinator trace-clock (a local bridge pair, sampled here).
        Without an offset estimate the track is skipped — alignment is
        deterministic or absent, never guessed."""
        trace = snap.get("trace")
        if not isinstance(trace, dict):
            return
        events = trace.get("events")
        if not isinstance(events, list) or not events:
            return
        off = self.offsets.offset(wid)
        if off is None:
            return
        try:
            t_board_w = float(snap["t_board"])
            t_trace_us_w = float(snap["t_trace_us"])
        except (KeyError, TypeError, ValueError):
            return
        shift_us = (
            (t_board_w * 1e6 - t_trace_us_w)
            - off * 1e6
            + (tracer.now_us() - self.clock.now() * 1e6)
        )
        tracer.set_worker_track(wid, events, shift_us)

    def _collect_tape(self, wid: str) -> None:
        """Post-mortem: pull the flight-recorder tape out of a dead
        worker's LAST posted snapshot and dump it locally — the tape a
        SIGKILLed worker could never write itself.  Once per worker;
        the snapshot key itself is swept by GC after the grace window."""
        if wid in self._tapes_collected:
            return
        self._tapes_collected.add(wid)
        snap = collect_worker_snapshot(self.board, wid)
        tape = snap.get("tape") if isinstance(snap, dict) else None
        if not tape:
            return
        path = dump_fleet_tape(wid, tape, "worker-dead")
        if path is not None:
            publish(
                "fleet.tape.collected",
                worker=wid,
                events=len(tape),
                path=path,
            )

    # -- re-dispatch + local fallback --------------------------------------

    def _redispatch(self, bid: str, reason: str) -> None:
        epoch = self.leases.bump(bid, self._tick)
        # The fencing epoch IS the attempt counter: epoch N means N
        # offers already failed.  Past the cap, the block takes the
        # typed dead-letter path — scored locally through the serve
        # loop's quarantine ladder (retry → degrade → poison bisection),
        # so a block no worker can ever finish still gets each of its
        # requests a terminal answer instead of re-offering forever.
        if epoch > self.max_redispatch:
            publish(
                "fleet.deadletter", block=bid, epoch=epoch, reason=reason
            )
            log_line(
                f"mpi_openmp_cuda_tpu: fleet: {bid} exhausted "
                f"{self.max_redispatch} re-dispatch attempts "
                f"(last: {reason}); dead-lettering to the local "
                "quarantine ladder"
            )
            self._finish_local(bid)
            return
        publish("fleet.redispatch", block=bid, epoch=epoch, reason=reason)
        if self.membership.live_count() > 0:
            try:
                self._post_offer(bid, epoch, self.blocks[bid])
            except OSError:
                # Unpostable board (ENOSPC): the lease stays bumped, so
                # the next expiry retries the post — and the attempt cap
                # above still bounds the loop.
                log_line(
                    f"mpi_openmp_cuda_tpu: fleet: re-offer of {bid} "
                    "failed to post; will retry at next lease expiry"
                )
            return
        log_line(
            f"mpi_openmp_cuda_tpu: fleet: no live workers for {bid}; "
            "scoring locally on the coordinator"
        )
        self._finish_local(bid)

    def _finish_local(self, bid: str) -> None:
        """Score one outstanding block on the coordinator through the
        serve loop's sync path (retry → degrade → bisection — the full
        quarantine ladder).  The lease was already bumped, so any
        straggler's later post lands fenced."""
        block = self.blocks.pop(bid)
        self._phase_marks.pop(bid, None)  # local scoring has no phases
        lease = self.leases.get(bid)
        self._retired.append((bid, lease.epoch))
        self.leases.retire(bid)
        self.board.delete(offer_key(bid))
        self._local_score(block)

    def finish_locally(self) -> None:
        """Drain: fence (epoch bump) and locally score every outstanding
        superblock, so in-flight requests finish before the drain
        journal is written and no worker post can land after resume."""
        for bid in list(self.blocks):
            self.leases.bump(bid, self._tick)
            self._finish_local(bid)

    # -- failover: checkpoint + board GC -----------------------------------

    def checkpoint(self, raws, answered) -> None:
        """Post the takeover replay state: every admitted-but-unanswered
        request (raw dicts, replayable through ``ingest``) plus the
        answered reply ids (the successor's idempotency set).  Change-
        cached — a quiet tick costs no board write — and best-effort on
        a sick board: the ``--journal`` file stays authoritative for
        same-process resume; this board copy is the one a STANDBY can
        reach."""
        if self.leader is None:
            return
        blob = json.dumps({
            "gen": self.gen,
            "requests": list(raws),
            "answered": list(answered),
        })
        if blob == self._ckpt_blob:
            return
        try:
            self.board.post(ckpt_key(self.gen), blob)
            self._ckpt_blob = blob
        except OSError:
            pass

    @staticmethod
    def _bid_gen(bid: str) -> int:
        """The leader generation stamped into a block id
        (``g<gen>b<seq>``); ids without a stamp read as generation 0."""
        m = re.match(r"^g(\d+)b", bid)
        return int(m.group(1)) if m else 0

    def _gc_verdict(self, rel: str) -> str:
        """Classify one board key (relative to the fleet root):
        ``keep``, ``sweep`` (delete past the grace window), or ``fence``
        (sweep + count once as a dead generation's fenced post)."""
        parts = rel.split("/")
        kind = parts[0]
        if kind in ("worker", "hb"):
            view = self.membership.workers.get(parts[-1])
            if view is not None and not view.alive:
                return "sweep"  # a dead worker's registration/beat
            return "keep"  # live, or not yet observed (still joining)
        if kind == "obssnap":
            view = self.membership.workers.get(parts[-1])
            if view is not None and not view.alive:
                # Swept only past the grace window (gc_ticks), which is
                # after the death-tick tape collection by construction.
                return "sweep"
            return "keep"  # a live worker's snapshot, overwritten in place
        if kind in ("leader", "leaderhb", "ckpt"):
            gen = _gen_of(parts[-1])
            if gen is not None and gen < self.gen:
                return "sweep"  # a retired generation's record
            return "keep"
        if kind in ("offer", "claim", "result"):
            bid = parts[1] if len(parts) > 1 else ""
            gen = self._bid_gen(bid)
            if gen > self.gen:
                return "keep"  # a successor's namespace: never touch
            if gen < self.gen:
                return "fence"  # dead leader's debris: count, then sweep
            if bid in self.blocks:
                if kind == "offer":
                    return "keep"
                epoch = _epoch_of(parts[-1])
                if epoch is not None and self.leases.admits(bid, epoch):
                    return "keep"  # the live lease's claim/result keys
                return "sweep"  # a fenced previous epoch's debris
            return "sweep"  # retired bid: whatever it left is debris
        return "keep"  # shutdown key, unknown shapes: not GC's business

    def _gc(self, tick: int) -> None:
        """Epoch-aware board GC, one pass per pump tick.  A key first
        classified sweepable at tick T is deleted at T + ``gc_ticks``
        (default two lease windows) — late enough that ``_fence_stale``
        has counted any zombie post and a mid-join worker is not
        confused, early enough that the board stays bounded across
        leader generations."""
        swept = 0
        for key in self.board.keys(FLEET_PREFIX):
            verdict = self._gc_verdict(key[len(FLEET_PREFIX):])
            if verdict == "keep":
                self._gc_marks.pop(key, None)
                continue
            if verdict == "fence" and key not in self._gc_fenced:
                self._gc_fenced.add(key)
                publish("leader.fenced", key=key, gen=self.gen)
                log_line(
                    "mpi_openmp_cuda_tpu: fleet: fenced dead-generation "
                    f"post {key} (current gen {self.gen})"
                )
            mark = self._gc_marks.setdefault(key, tick)
            if tick - mark >= self.gc_ticks:
                self.board.delete(key)
                self._gc_marks.pop(key, None)
                swept += 1
        if swept:
            publish("board.gc", count=swept, gen=self.gen)

    def gc_final(self) -> None:
        """Clean-completion sweep (no grace): everything this run could
        have left on the board EXCEPT the worker registry (workers are
        still alive until the shutdown key lands), the shutdown key,
        and the surviving generations' leader claim + beat — the
        board's monotonic generation record.  This is what makes
        ``make fleet-chaos``'s no-stale-keys gate hold without keeping
        the loop alive for a grace window.

        A zombie's stale post can land in the window between its
        block's retirement and this sweep; probe the retired set one
        last time so such a post is fence-COUNTED before it is
        deleted, never silently swallowed."""
        self._probe_retired()
        swept = 0
        for key in self.board.keys(FLEET_PREFIX):
            parts = key[len(FLEET_PREFIX):].split("/")
            if parts[0] in ("worker", "hb", "shutdown"):
                continue
            if parts[0] in ("leader", "leaderhb"):
                gen = _gen_of(parts[-1])
                if gen is None or gen >= self.gen:
                    continue
            self.board.delete(key)
            swept += 1
        sweep = getattr(self.board, "sweep_orphans", None)
        if sweep is not None:
            swept += int(sweep() or 0)
        if swept:
            publish("board.gc", count=swept, gen=self.gen, final=True)

    def shutdown(self) -> None:
        """End of run: tell workers to exit.  Best-effort — a worker
        that never sees the key still exits on its own drain signal.
        A DEPOSED leader must not post it: the fleet belongs to the
        successor now, and this key would kill ITS workers."""
        if self._deposed:
            return
        try:
            self.board.post(shutdown_key(), "shutdown")
        except OSError:
            pass


def standby_wait(board, leader, clock, poll_s=_POLL_S):
    """The ``--fleet-standby`` watch loop: poll the newest leader
    generation's beat under the membership staleness rule until one of

    * ``("takeover", gen)`` — the watched leader went silent for a full
      deadline and THIS standby won the claim on generation ``gen + 1``
      (``leader`` now holds it; the caller replays gen ``gen``'s
      checkpoint and starts serving);
    * ``("shutdown", None)`` — the fleet completed cleanly (the leader
      posted the shutdown key): exit 0, nothing to take over;
    * ``("drain", None)`` — this standby itself was drain-signalled.

    Losing the takeover race is not an exit: a rival standby won, and
    the watch simply restarts against the new leader's beat.
    """
    tick = 0
    while True:
        if drain_requested():
            return ("drain", None)
        if board.get(shutdown_key()) is not None:
            return ("shutdown", None)
        tick += 1
        if leader.observe(tick):
            watched = leader.watched_gen()
            if leader.try_acquire(watched + 1):
                return ("takeover", watched)
        _pause(clock, poll_s, drain_requested)


class FleetWorker:
    """One scoring worker's loop state (single-threaded, no locks).

    register → heartbeat → scan offers → claim → score → post, forever;
    exits when the coordinator posts the shutdown key or this process
    is drain-signalled.  A superblock whose scoring fails past the
    whole retry/degrade ladder is simply never posted — the
    coordinator's lease expiry re-dispatches it, which is the fleet's
    failure model for sick workers too.
    """

    def __init__(self, board, pipeline, policy, clock=None):
        self.board = board
        self.pipeline = pipeline
        self.policy = policy
        self.clock = clock or ServeClock()
        self.wid = f"w{os.getpid()}"
        self.poll_s = env_float("SEQALIGN_WORKER_HEARTBEAT_S", 0.02)
        self._beat = 0
        self._done: set[tuple[str, int]] = set()
        self._zombie = False  # chaos: freeze heartbeats, earn the verdict
        self._zombie_done = False
        # Observability-snapshot cadence, expressed in heartbeats so the
        # snapshot rides the existing pulse thread (one board write per
        # cadence, overwriting in place — the board holds one snapshot).
        snap_s = env_float("SEQALIGN_FLEET_OBSSNAP_S", 0.25)
        self._snap_beats = max(1, round(snap_s / self.poll_s))

    def register(self) -> None:
        self.board.post(
            worker_key(self.wid),
            json.dumps({"wid": self.wid, "pid": os.getpid()}),
        )
        log_line(
            f"mpi_openmp_cuda_tpu: fleet: worker {self.wid} registered"
        )

    def heartbeat(self) -> None:
        self._beat += 1
        try:
            self.board.post(heartbeat_key(self.wid), str(self._beat))
        except OSError:
            # A board that cannot take the beat (ENOSPC) earns this
            # worker the same death verdict a crash would — the correct
            # outcome, reached without killing the heartbeat thread.
            pass

    def post_obs_snapshot(self) -> None:
        """Post this worker's bounded observability snapshot (metrics +
        recent trace events + the flight-recorder tape) next to its
        heartbeat.  Best-effort, same stance as the beat: a board that
        cannot take the write costs granularity, never the worker.  The
        RuntimeError arm covers snapshotting the registry while the
        scoring thread mutates it (the telemetry module's documented
        lock-free-copy hazard) — the next cadence simply retries."""
        try:
            post_worker_snapshot(
                self.board, self.wid, float(self.clock.now()),
                beat=self._beat,
            )
        except (OSError, RuntimeError):
            pass

    def should_exit(self) -> bool:
        return (
            drain_requested()
            or self.board.get(shutdown_key()) is not None
        )

    def _heartbeat_loop(self, stop) -> None:
        """Daemon-thread heartbeat: liveness must not depend on scoring
        progress — a worker busy compiling its first superblock is
        alive; only a killed (thread dies with the process) or zombie
        (``_zombie`` frozen deliberately) worker goes silent."""
        while not stop.is_set():
            if not self._zombie:
                self.heartbeat()
                if self._beat % self._snap_beats == 0:
                    self.post_obs_snapshot()
            _pause(self.clock, self.poll_s, stop.is_set)

    def run(self) -> int:
        self.register()
        stop = threading.Event()
        pulse = threading.Thread(
            target=self._heartbeat_loop, args=(stop,), daemon=True
        )
        pulse.start()
        try:
            while True:
                if self.should_exit() or self._zombie_done:
                    log_line(
                        "mpi_openmp_cuda_tpu: fleet: worker "
                        f"{self.wid} exiting"
                    )
                    return 0
                if not self.step():
                    _pause(self.clock, self.poll_s, drain_requested)
        finally:
            stop.set()
            # The leader's clean-completion sweep (gc_final) runs BEFORE
            # the shutdown key lands, so a heartbeat-cadence snapshot
            # posted in that window would outlive the run and trip the
            # no-stale-keys gate — the worker retires its own snapshot
            # once the pulse thread has stopped posting.
            pulse.join(timeout=2 * self.poll_s + 1.0)
            try:
                self.board.delete(obs_snapshot_key(self.wid))
            except OSError:
                pass  # advisory: a vanished board costs hygiene, not the run

    def step(self) -> bool:
        """Scan the offer board once; claim and score anything new.
        Returns True if any work was attempted (the run loop only
        pauses on an empty scan)."""
        worked = False
        for key in self.board.keys(OFFER_PREFIX):
            offer = board_read_json(self.board, key)
            if offer is None:
                continue  # torn offer reads as missing
            bid = str(offer.get("bid", ""))
            epoch = offer.get("epoch")
            if not bid or not isinstance(epoch, int):
                continue
            if (bid, epoch) in self._done:
                continue
            if self.board.get(result_key(bid, epoch)) is not None:
                self._done.add((bid, epoch))
                continue
            if self.board.get(claim_key(bid, epoch)) is not None:
                continue  # someone else holds this epoch
            if not self.board.claim(
                claim_key(bid, epoch),
                # t_claim echoes the offer on THIS worker's clock — the
                # second half of the estimator's offer/claim pair.
                json.dumps({
                    "wid": self.wid,
                    "epoch": epoch,
                    "t_claim": float(self.clock.now()),
                }),
            ):
                continue  # lost the race: exactly one winner per epoch
            self._done.add((bid, epoch))
            worked = True
            self._score_claim(offer, bid, epoch)
        return worked

    def _score_claim(self, offer: dict, bid: str, epoch: int) -> None:
        if _fault_scheduled("lease:stall"):
            # Chaos: hold the claim and never score — the coordinator's
            # lease expiry must fence this epoch and re-dispatch.
            log_line(
                f"mpi_openmp_cuda_tpu: fleet: worker {self.wid} stalling "
                f"its lease on {bid} (chaos)"
            )
            return
        # kill:fleet-worker rides this fire point: SIGKILL mid-superblock,
        # after the claim and before any result lands.
        _fault_fire("fleet_score")
        zombie = _fault_scheduled("zombie:fleet-worker")
        t_score = float(self.clock.now())
        publish(
            "fleet.score.start", block=bid, epoch=epoch, worker=self.wid
        )
        try:
            rows = self._score_offer(offer, epoch)
        except Exception as e:
            # advisory: the claim stays leased — lease expiry re-dispatches
            # the superblock; a worker must not die on one bad block.
            log_line(
                f"mpi_openmp_cuda_tpu: fleet: worker {self.wid}: "
                f"superblock {bid} failed ({e}); leaving it to lease "
                "re-dispatch"
            )
            return
        if zombie:
            self._zombie = True  # heartbeats freeze: earn the death verdict
            self._outlive_lease(bid, epoch)
        payload = json.dumps({
            "bid": bid,
            "epoch": int(epoch),
            "wid": self.wid,
            "rows": rows.tolist(),
            # The result is the work unit coming BACK over the board:
            # echo the propagated trace ids (SEQ015) and stamp the
            # score/post times for the coordinator's phase breakdown.
            "traces": _offer_traces(offer),
            "t_score": t_score,
            "t_post": float(self.clock.now()),
        })
        if _fault_scheduled("board:torn-post"):
            # Chaos: a writer dying mid-post on a non-atomic board —
            # half the bytes land.  Every reader must treat this as
            # MISSING; the lease expires and the block re-dispatches.
            self.board.post(result_key(bid, epoch), payload[: len(payload) // 2])
            return
        try:
            self.board.post(result_key(bid, epoch), payload)
        except OSError as e:
            # Disk-full mid-post: the key reads as missing (the atomic
            # post never completed), so the lease expiry re-dispatches —
            # the same recovery as a worker death, minus the death.
            log_line(
                f"mpi_openmp_cuda_tpu: fleet: worker {self.wid}: result "
                f"post for {bid} failed ({e}); leaving it to lease "
                "re-dispatch"
            )
            return
        if zombie:
            # The stale post landed (it MUST read as fenced); a declared-
            # dead worker has no further business claiming fresh work.
            self._zombie_done = True

    def _score_offer(self, offer: dict, epoch: int = 0):
        # np.asarray keeps these HOST-side: the donation-safety pass
        # (analysis/dataflow.py) proves this root re-stages device
        # buffers at _score_local on every retry, so the jit entry
        # points may donate.  Don't "optimise" to jnp here.
        seq1 = np.asarray(offer["seq1"], dtype=np.int8)
        codes = [np.asarray(r, dtype=np.int8) for r in offer["rows"]]
        weights = [int(w) for w in offer["weights"]]
        budget = self.policy.new_budget()
        # The propagated context: worker-side spans and launch rows are
        # stamped with the ORIGINATING request trace ids plus this
        # worker's identity and lease epoch, so the coordinator's merged
        # timeline links its admission spans to the remote launches.
        links = [str(r) for r in (offer.get("links") or ())]
        ctx = {
            "traces": _offer_traces(offer),
            "worker": self.wid,
            "epoch": int(epoch),
        }
        with span("score.fleet.superblock"):
            promise = self.pipeline.dispatch(
                seq1, codes, weights, budget, links=links, trace_ctx=ctx
            )
            return np.asarray(
                self.pipeline.materialise(
                    promise, seq1, codes, weights, budget
                ),
                dtype=np.int64,
            )

    def _outlive_lease(self, bid: str, epoch: int) -> None:
        """Chaos zombie: sit on the scored result (heartbeats stopped —
        the frozen beat is what earns the death verdict) until the
        coordinator has moved past this epoch, then let the caller post
        it anyway.  The post MUST land fenced, never demuxed."""
        log_line(
            f"mpi_openmp_cuda_tpu: fleet: worker {self.wid} going zombie "
            f"on {bid} epoch {epoch} (chaos)"
        )
        while not self.should_exit():
            offer = board_read_json(self.board, offer_key(bid))
            if offer is None or offer.get("epoch") != epoch:
                return  # fenced (re-offered or finished): post stale now
            _pause(self.clock, self.poll_s, drain_requested)


def run_fleet_worker(args, timer, policy, deg) -> int:
    """CLI entry for ``--fleet-worker`` (io/cli.py run(); obs, faults,
    and the drain guard are already armed there)."""
    from ..io.pipeline import ChunkPipeline
    from ..resilience.rescue import FileBoard

    worker = FleetWorker(
        FileBoard(args.fleet_board),
        ChunkPipeline(policy, deg),
        policy,
    )
    with timer.phase("serve"):
        rc = worker.run()
    timer.report()
    return rc
