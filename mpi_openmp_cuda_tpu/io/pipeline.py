"""Shared superblock submit/finish machinery (stream CLI + serve loop).

Factored out of the ``--stream`` closure nest in :mod:`.cli` so the
batch-streaming path and the serving plane drive ONE implementation of
the dispatch/materialise contract instead of a copy:

* :class:`ChunkPipeline` — async-dispatch and materialise one
  shape-uniform chunk under a SHARED retry budget, with the
  ``--degrade`` backend chain applied at both stages and the oracle
  re-verification hook on the first degraded result.  All scoring goes
  through ``degrader.scorer`` *at call time*: a mid-stream degradation
  replaces the backend for every later chunk too.
* :class:`PendingWindow` — the bounded in-flight window: each pushed
  promise's device→host copy is prefetched at dispatch, the oldest
  entry is finished once the window overflows, and ``flush()`` drains
  the rest.  On a tunnelled TPU each result fetch costs a ~0.1 s link
  round trip; the window gives the prefetched copies time to land
  before ``finish`` needs them (measured 6.3x over batch mode with a
  window of one, r5).
* :class:`FeedStager` — true feed overlap (r6): stage superblock
  N+1's host→device transfers (async ``jax.device_put`` via
  ``AlignmentScorer.prestage_codes``) while superblock N computes, for
  the batch, ``--stream`` and serve-batcher paths alike.  Purely
  advisory and single-use: the dispatch ignores a handle whose planned
  shapes drifted, and retries always re-stage from host (donation
  contract).
"""

from __future__ import annotations

import collections

from ..obs.trace import trace_launch_begin, trace_launch_end
from ..resilience.degrade import (
    MaterialisedRows,
    run_degrading,
    verify_rows_against_oracle,
)
from ..resilience.policy import FATAL_ERROR_TYPES


class ChunkPipeline:
    """One run's dispatch/materialise pair over a policy + degrader.

    ``breaker`` (serve mode, --degrade only) is the circuit breaker
    over the primary dispatch path: every primary attempt's transient
    failure/success feeds it, and while it is OPEN dispatch bypasses
    the primary entirely — the pinned degraded scorer is called
    directly under the plain retry policy, skipping the
    attempt-exhaust-degrade-reverify ladder per superblock.
    """

    def __init__(self, policy, degrader, breaker=None):
        self.policy = policy
        self.degrader = degrader
        self.breaker = breaker

    def _guard(self, fn):
        """Wrap one attempt so the breaker sees the primary path's
        health: transient failures count toward opening; fatal errors
        (ValueError/TypeError — bad input, oracle mismatch) are NOT a
        backend-health signal and pass through unrecorded."""
        if self.breaker is None:
            return fn

        def guarded():
            try:
                result = fn()
            except FATAL_ERROR_TYPES:
                raise
            except Exception:
                # BaseException (drain, interrupt) passes through
                # unrecorded — process lifecycle, not backend health.
                self.breaker.record_failure()
                raise
            self.breaker.record_success()
            return result

        return guarded

    def _verify(self, seq1_codes, codes, weights):
        """Oracle re-verification closure for the first degraded chunk
        (None when --degrade is off: run_degrading skips the check)."""
        if not self.degrader.enabled:
            return None
        return lambda rows: verify_rows_against_oracle(
            seq1_codes, codes, weights, rows
        )

    def dispatch(
        self, seq1_codes, codes, weights, budget, links=None, staged=None,
        trace_ctx=None,
    ):
        """Async-dispatch a chunk under the shared budget; on budget
        exhaustion with --degrade, fall down the backend chain with a
        synchronous rescore — MaterialisedRows keeps the promise
        contract for :meth:`materialise`.  ``links`` is the list of
        request ids riding this launch (serve mode; None in batch/
        stream), recorded on the trace plane's launch span.
        ``trace_ctx`` is the propagated fleet stamp (originating trace
        ids, worker id, lease epoch) a --fleet-worker threads onto its
        launch rows; None everywhere else so local rows are unchanged.

        Donation anchor: ``seq1_codes``/``codes`` stay HOST arrays all
        the way down this ladder — every (re)dispatch re-stages fresh
        device buffers at ``AlignmentScorer._score_local``, which is
        what lets the jit entry points donate their operands.  Staging
        here (above the retry boundary) would hand a retried attempt an
        already-donated buffer; ``make donation-audit`` flags exactly
        that (restage_paths / stage-above-retry).

        ``staged`` (feed overlap) is an ``ops.dispatch.StagedFeed`` of
        operands whose transfers a :class:`FeedStager` already started —
        compatible with the donation anchor because the handle is
        SINGLE-USE: the first attempt drains it, so a retried attempt
        finds it empty and re-stages from the host arrays exactly as
        before.  Only the primary async path consumes it; the degraded
        and breaker-open paths score from host operands."""
        deg = self.degrader
        if self.breaker is not None and self.breaker.bypass_primary():
            # Breaker open: straight to the pinned degraded backend.
            # Synchronous scoring (the degraded contract), one oracle
            # check the first time only — NOT per request.
            rows = self.policy.run(
                lambda: deg.scorer.score_codes(seq1_codes, codes, weights),
                "chunk dispatch [breaker-open]",
                budget=budget,
            )
            if deg.enabled and not deg.verified:
                verify_rows_against_oracle(seq1_codes, codes, weights, rows)
                deg.verified = True
            promise = MaterialisedRows(rows)
        else:
            promise = run_degrading(
                self.policy,
                deg,
                self._guard(
                    lambda: deg.scorer.score_codes_async(
                        seq1_codes, codes, weights, staged=staged
                    )
                ),
                lambda sc: sc.score_codes(seq1_codes, codes, weights),
                "chunk dispatch",
                budget=budget,
                verify=self._verify(seq1_codes, codes, weights),
                wrap=MaterialisedRows,
            )
        # Keyed by the promise's identity: materialise closes the same
        # key, and the entry is popped there, so id reuse after
        # retirement cannot collide.
        trace_launch_begin(
            id(promise),
            links=links or (),
            len1=seq1_codes.size,
            lens=[c.size for c in codes],
            ctx=trace_ctx,
        )
        return promise

    def materialise(self, promise, seq1_codes, codes, weights, budget):
        """Materialise under the chunk's shared budget (first attempt
        forces the promise, retries rescore synchronously), degrading
        past exhaustion like :meth:`dispatch`.  Same donation anchor as
        :meth:`dispatch`: operands are host arrays, retries re-stage."""
        deg = self.degrader
        first = [promise]

        def attempt():
            if first:
                return first.pop().result()
            return deg.scorer.score_codes(seq1_codes, codes, weights)

        rows = run_degrading(
            self.policy,
            deg,
            self._guard(attempt),
            lambda sc: sc.score_codes(seq1_codes, codes, weights),
            "chunk scoring",
            budget=budget,
            verify=self._verify(seq1_codes, codes, weights),
        )
        # Host rows in hand: the measured launch wall is dispatch ->
        # here (retries and degradation included — honest accounting).
        trace_launch_end(id(promise))
        return rows


class PendingWindow:
    """Bounded in-flight promises; ``finish`` is called with exactly the
    tuple that was pushed, oldest first."""

    def __init__(self, depth: int, finish):
        self.depth = max(1, int(depth))
        self._finish = finish
        self._pending = collections.deque()

    def __len__(self) -> int:
        return len(self._pending)

    def push(self, promise, *rest) -> None:
        if promise is not None:
            try:
                promise.prefetch()
            except Exception:
                # advisory: prefetch is purely a latency optimisation: a
                # device->host copy that cannot start here resurfaces at
                # result(), inside the chunk's shared retry budget,
                # instead of killing the pipeline from an advisory call.
                pass
        self._pending.append((promise, *rest))
        if len(self._pending) > self.depth:
            self._finish(*self._pending.popleft())

    def flush(self) -> None:
        while self._pending:
            self._finish(*self._pending.popleft())


def feed_overlap_enabled() -> bool:
    """Feed overlap (prestaging the next superblock's host→device
    transfers) is ON by default; ``TPU_SEQALIGN_FEED_OVERLAP=0``
    disables it (A/B hook, and the escape hatch if a runtime's
    device_put is synchronous enough to serialise the pipeline)."""
    from ..utils.platform import env_flag

    return env_flag("TPU_SEQALIGN_FEED_OVERLAP")


class FeedStager:
    """Starts the NEXT chunk's host→device transfers while the current
    chunk computes (feed overlap, r6).

    Wraps ``degrader.scorer.prestage_codes`` — resolved at call time
    like all pipeline scoring, so a mid-stream degradation stops
    prestaging for the replaced backend automatically.  Every failure
    mode is advisory: a backend without ``prestage_codes``, a planning
    error, or disabled overlap all return None, and the dispatch then
    stages from host exactly as before.  The returned handle must feed
    AT MOST ONE :meth:`ChunkPipeline.dispatch` call (single-use
    donation contract)."""

    def __init__(self, degrader, enabled: bool | None = None):
        self.degrader = degrader
        self.enabled = (
            feed_overlap_enabled() if enabled is None else bool(enabled)
        )

    def stage(self, seq1_codes, codes, weights):
        if not self.enabled or not codes:
            return None
        scorer = getattr(self.degrader, "scorer", None)
        prestage = getattr(scorer, "prestage_codes", None)
        if prestage is None:
            return None
        try:
            return prestage(seq1_codes, codes, weights)
        except Exception:
            # advisory: prestaging is purely a latency optimisation — any
            # resurfaces (if real) at dispatch, inside the chunk's
            # shared retry budget, not here.
            return None
