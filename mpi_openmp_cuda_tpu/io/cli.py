"""CLI driver (reference parity: L4/L5 orchestration, main.c main()).

``python -m mpi_openmp_cuda_tpu < input.txt`` reproduces the reference's
``mpiexec -np 2 ./final < input.txt`` contract: results on stdout in the
exact ``#i: score: S, n: N, k: K`` format, diagnostics on stderr, non-zero
exit on any failure (the C11 fail-stop stance).  Optional flags extend the
contract without breaking it (SURVEY §5 config tier).
"""

from __future__ import annotations

import argparse
import signal
import sys

from ..obs import arm_observability, disarm_observability
from ..obs import export as obs_export
from ..obs import flightrec as obs_flightrec
from ..obs import trace as obs_trace
from ..obs.metrics import gauge as obs_gauge
from ..ops.dispatch import AlignmentScorer
from ..resilience.degrade import (
    BackendDegrader,
    run_degrading,
    verify_rows_against_oracle,
)
from ..resilience.drain import DrainInterrupt, drain_guard, drain_requested
from ..resilience.faults import activate_faults, deactivate_faults, parse_spec
from ..resilience.policy import RetryPolicy
from ..resilience.watchdog import (
    DeadlineExpiredError,
    activate_watchdog,
    deactivate_watchdog,
)
from ..utils.platform import env_flag, env_float, env_int, env_str
from ..utils.profiling import PhaseTimer, device_trace
from .parse import load_problem
from .pipeline import ChunkPipeline, FeedStager, PendingWindow
from .printer import guarded_stdout, print_results, write_json_sidecar


def _nonnegative_int(text: str) -> int:
    value = int(text)
    if value < 0:
        raise argparse.ArgumentTypeError(f"must be >= 0, got {value}")
    return value


def _positive_int(text: str) -> int:
    value = int(text)
    if value < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {value}")
    return value


def _positive_float(text: str) -> float:
    value = float(text)
    if value <= 0:
        raise argparse.ArgumentTypeError(f"must be > 0, got {value}")
    return value


# BSD sysexits the driver's supervisor can script against: 75 (EX_TEMPFAIL)
# says "rerun me" — a drained preemption or a deadline-rooted exhaustion
# leaves a resumable journal behind — while 65 (EX_DATAERR) stays the
# fail-stop verdict for everything else and 64 (EX_USAGE) rejects flag
# combinations before any expensive phase.  1 remains the broken-pipe
# exit (downstream closed the stream; nothing of ours failed) and
# argparse keeps its own 2.
EX_OK = 0
EX_USAGE = 64
EX_FATAL = 65
EX_TEMPFAIL = 75


def _sigusr2_dump(signum, frame) -> None:
    """SIGUSR2 → dump the flight recorder NOW: live triage of a stuck
    process without killing it (no-op when the recorder is not armed).
    Registered only while the observability plane is armed."""
    obs_flightrec.dump_active("sigusr2")


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_openmp_cuda_tpu",
        description="TPU-native batch sequence-alignment scorer "
        "(stdin/stdout contract of the MPI+OpenMP+CUDA reference).",
    )
    p.add_argument(
        "--input",
        default=None,
        help="input file (default: stdin, like the reference's './final < input.txt')",
    )
    p.add_argument(
        "--backend",
        choices=("auto", "xla", "xla-gather", "pallas", "oracle"),
        default="auto",
        help="compute path (default auto: fused Pallas TPU kernel on a "
        "real TPU, pure-XLA MXU formulation elsewhere); or force xla, "
        "xla-gather, pallas, or the host numpy oracle",
    )
    p.add_argument(
        "--mesh",
        default=None,
        help="device mesh: 'N' or 'batch:N' shards the Seq2 batch over N "
        "devices (data parallel); 'seq:N' ring-shards Seq1 over N devices "
        "(sequence/context parallel); 'DxS' composes both on a 2-D mesh "
        "(default: no sharding, single device)",
    )
    p.add_argument(
        "--distributed",
        action="store_true",
        help="call jax.distributed.initialize() first (multi-host, the runOn2 analogue)",
    )
    p.add_argument(
        "--json",
        default=None,
        metavar="PATH",
        help="also write results as a JSON sidecar file",
    )
    p.add_argument(
        "--journal",
        default=None,
        metavar="PATH",
        help="per-sequence result journal enabling resume after preemption; "
        "composes with --distributed (the coordinator owns the file and "
        "broadcasts the resume schedule to every host)",
    )
    p.add_argument(
        "--profile",
        action="store_true",
        help="print per-phase wall-clock timings to stderr",
    )
    p.add_argument(
        "--trace",
        default=None,
        metavar="DIR",
        help="capture a jax.profiler device trace of the scoring phase "
        "into DIR (view with TensorBoard / xprof)",
    )
    p.add_argument(
        "--trace-out",
        default=None,
        metavar="PATH",
        help="write a request-scoped Perfetto/Chrome-trace JSON timeline "
        "to PATH when the run exits (every exit code, like "
        "--metrics-out): host spans, bus events, per-request tracks, and "
        "per-launch measured-vs-cost-model rows with a gap_attribution "
        "summary (SEQALIGN_TRACE; implies --metrics; distinct from "
        "--trace, the jax.profiler device trace)",
    )
    p.add_argument(
        "--selfcheck",
        action="store_true",
        help="after scoring, rescore a deterministic sample on the host "
        "oracle and fail on any mismatch (sanitizer analogue)",
    )
    p.add_argument(
        "--retries",
        type=_nonnegative_int,
        default=0,
        metavar="N",
        help="retry the scoring phase up to N times on transient device "
        "failure (combine with --journal to resume mid-batch); under "
        "--distributed every host runs the same retry loop, so a "
        "job-wide transient failure (the SPMD norm) re-enters the "
        "collectives in lockstep; a failure confined to a single host "
        "desynchronises the schedules and is torn down by the "
        "jax.distributed coordination timeout — rerun with --journal to "
        "resume. Under --stream --distributed the same applies per "
        "chunk: workers retry independently of the coordinator, so a "
        "lone-host retry still ends in the coordination-timeout teardown",
    )
    p.add_argument(
        "--faults",
        default=None,
        metavar="SPEC",
        help="deterministic fault injection for chaos testing: "
        "'site:fail=N[,after=M][,kind=transient|fatal]' entries joined "
        "with ';' (e.g. 'chunk_scoring:fail=2;journal_append:fail=1'); "
        "the SEQALIGN_FAULTS env var supplies a spec when this flag is "
        "absent (with a retry floor from SEQALIGN_FAULT_RETRIES so a "
        "chaos suite run keeps its goldens); see "
        "mpi_openmp_cuda_tpu/resilience/faults.py for the site list",
    )
    p.add_argument(
        "--degrade",
        action="store_true",
        help="on retry-budget exhaustion, fall down the backend chain "
        "pallas -> xla -> xla-gather instead of failing, logging the "
        "fallback and re-verifying the first degraded chunk against the "
        "host oracle (single-process only: under --distributed the "
        "backend choice is the SPMD program itself)",
    )
    p.add_argument(
        "--stream",
        type=_positive_int,
        default=None,
        metavar="CHUNK",
        help="pipelined mode: parse and score CHUNK sequences at a time, "
        "overlapping host parsing with asynchronous device compute; live "
        "host memory is bounded by (window+1) x CHUNK sequences plus one "
        "buffered output line per result (window: in-flight chunks with "
        "prefetched device->host copies, TPU_SEQALIGN_STREAM_DEPTH, "
        "default 4 single-process / fixed 1 multi-host); byte-identical "
        "output, flushed after the whole stream succeeds (fail-stop: no "
        "partial results); under --distributed the coordinator "
        "broadcasts each chunk so every host's memory stays bounded; on "
        "a TUNNELLED device each chunk still pays a ~tens-of-ms launch "
        "round trip, so prefer CHUNK large enough that chunks are few "
        "unless memory-bound (measured: scripts/stream_bench.py)",
    )
    p.add_argument(
        "--deadline",
        type=_positive_float,
        default=None,
        metavar="S",
        help="watchdog deadline in seconds around device work and "
        "coordinator collectives: a block that exceeds it surfaces a "
        "transient deadline-expiry error into the normal --retries (and "
        "--degrade) machinery instead of hanging silently; "
        "SEQALIGN_DEADLINE_S supplies the value when this flag is "
        "absent; a run whose failure is rooted in deadline expiry exits "
        "75 (resumable) rather than 65",
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="assert that the --journal file already exists and resume "
        "from it (error if it is missing); plain --journal still resumes "
        "opportunistically but silently starts fresh on an absent file — "
        "after a preemption (exit 75 / SIGKILL) --resume makes a typo'd "
        "path loud instead of rescoring the whole batch",
    )
    p.add_argument(
        "--metrics",
        action="store_true",
        help="arm the observability plane: resilience counters, config "
        "gauges and per-phase spans collected for the run "
        "(SEQALIGN_METRICS; implied by --metrics-out and --heartbeat); "
        "off by default, and when off every instrumentation site is a "
        "single attribute check — no allocation on the hot path",
    )
    p.add_argument(
        "--metrics-out",
        default=None,
        metavar="PATH",
        help="write the versioned JSON run report to PATH (plus a "
        "PATH.prom Prometheus text sidecar) when the run exits — "
        "including failed (65) and preempted (75) exits, so the last "
        "report of a crashed run still tells the story "
        "(SEQALIGN_METRICS_OUT; implies --metrics)",
    )
    p.add_argument(
        "--heartbeat",
        type=_positive_float,
        default=None,
        metavar="S",
        help="emit a one-line '[obs] chunk I/N retries=R degraded=D' "
        "status to stderr from the watchdog monitor thread after every "
        "S quiet seconds (SEQALIGN_HEARTBEAT_S; implies --metrics and "
        "composes with --deadline on the same monitor thread)",
    )
    p.add_argument(
        "--serve",
        action="store_true",
        help="persistent serving mode: hold the scorer (and its warm jit "
        "caches) in a long-lived loop, read newline-delimited JSON "
        "alignment requests, coalesce concurrent requests' Seq2s into "
        "shared fixed-shape superblocks (bucketed continuous batching), "
        "and stream per-sequence result records back; requests arrive on "
        "a loopback socket (--port) or the --input pipe/stdin; SIGTERM "
        "drains: in-flight superblocks finish, queued requests are "
        "journaled (--journal) and the run exits 75 for a --resume rerun",
    )
    p.add_argument(
        "--port",
        type=_nonnegative_int,
        default=None,
        metavar="PORT",
        help="with --serve: listen for request connections on "
        "127.0.0.1:PORT (0 = OS-assigned; the bound port is announced on "
        "stderr); SEQALIGN_SERVE_PORT supplies the value when this flag "
        "is absent; without a port the server reads requests from "
        "--input/stdin and exits when the pipe drains",
    )
    p.add_argument(
        "--telemetry-port",
        type=_nonnegative_int,
        default=None,
        metavar="PORT",
        help="with --serve: also serve a read-only plain-HTTP telemetry "
        "endpoint on 127.0.0.1:PORT (0 = OS-assigned; announced on "
        "stderr): GET /metrics is a live Prometheus scrape of the armed "
        "registry, /healthz and /trace answer JSON; the same data rides "
        'the serve socket itself as {"cmd": "metrics"|"healthz"|"trace"} '
        "verbs (SEQALIGN_TELEMETRY_PORT)",
    )
    p.add_argument(
        "--fleet-board",
        default=None,
        metavar="DIR",
        help="directory for the fleet coordination board (atomic "
        "file-backed key-value posts; no jax.distributed needed). With "
        "--serve this loop becomes the fleet COORDINATOR: planned "
        "superblocks are offered on the board under expiring leases "
        "(SEQALIGN_LEASE_S), scored by --fleet-worker processes, and "
        "results are fenced by lease epoch so a dead or zombie worker "
        "can never lose or double-answer a request; with no live "
        "workers every block scores locally. With --fleet-worker it "
        "names the board to claim work from.",
    )
    p.add_argument(
        "--fleet-worker",
        action="store_true",
        help="run as an elastic-fleet scoring worker: register on the "
        "--fleet-board, heartbeat (SEQALIGN_WORKER_HEARTBEAT_S), claim "
        "offered superblocks under lease epochs, score them through the "
        "shared chunk pipeline (same retry/degrade ladder as --serve), "
        "and post epoch-stamped results; joins mid-serve and exits when "
        "the coordinator posts shutdown (combine with --prewarm to join "
        "warm from the AOT manifest)",
    )
    p.add_argument(
        "--fleet-standby",
        action="store_true",
        help="run as a STANDBY fleet coordinator: watch the active "
        "leader's beat on the --fleet-board, and when it goes silent "
        "for a full lease window (SEQALIGN_LEASE_S), claim the next "
        "leader generation, replay the dead leader's board checkpoint "
        "(unanswered requests + answered reply ids), fence its late "
        "posts by generation, and resume serving with zero duplicate "
        "and zero dropped replies; exits 0 when the fleet shuts down "
        "cleanly instead (--port/--telemetry-port open immediately, so "
        "clients can reconnect-and-redrive before the takeover lands)",
    )
    p.add_argument(
        "--check",
        action="store_true",
        help="validate every concrete dispatch decision against the "
        "static-analysis contracts before launch (feed/exactness/rowpack/"
        "superblock gates plus the VMEM footprint model in "
        "mpi_openmp_cuda_tpu/analysis); the SEQALIGN_CHECK env var "
        "enables the same checks when this flag is absent",
    )
    p.add_argument(
        "--prewarm",
        action="store_true",
        help="AOT-compile the scorer's executables at process start "
        "(manifest replay + the problem's warm set) through JAX's "
        "persistent compilation cache, so a restarted process — an "
        "autoscaled serve replica, or a drain->--resume rerun — answers "
        "its first request without paying the multi-second first-compile "
        "tax; under --serve the steady-recompile gate then holds from "
        "the FIRST tick (SEQALIGN_PREWARM; cache home: "
        "SEQALIGN_CACHE_DIR)",
    )
    return p


class FeatureUnavailableError(RuntimeError):
    pass


def _is_resumable(e: BaseException | None) -> bool:
    """True when a failure chain is rooted in a watchdog deadline expiry:
    the input was never judged bad — the run was preempted by time — so
    the supervisor contract is exit 75 (rerun, with --resume under
    --journal) rather than the fatal 65.  Walks ``__cause__`` /
    ``__context__`` because expiries surface wrapped (RetryExhaustedError
    chains the last attempt's error as its cause)."""
    seen: set[int] = set()
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if isinstance(e, DeadlineExpiredError):
            return True
        e = e.__cause__ or e.__context__
    return False


def _check_resume(args) -> None:
    """``--resume`` turns resuming from an option into an assertion: the
    journal file must already exist.  Plain ``--journal`` starting fresh
    on an absent file is right for a FIRST run, but after a preemption a
    mistyped path would silently rescore everything — the opposite of
    what the operator asked for."""
    import os

    if args.resume and not os.path.exists(args.journal):
        raise FileNotFoundError(
            f"--resume: journal {args.journal!r} does not exist (a first "
            "run takes --journal alone; --resume asserts there is prior "
            "progress to reuse)"
        )


def _build_policy(args) -> tuple[RetryPolicy, str | None]:
    """Resolve the run's RetryPolicy and fault spec.

    Retry classification, the shared-budget contract and the lockstep
    backoff all live in resilience.policy (this CLI's old ``_retrying`` /
    ``_materialise_retrying`` helpers, unified).  The fault spec comes
    from ``--faults``, else the SEQALIGN_FAULTS env var; only the
    env-sourced spec gets the SEQALIGN_FAULT_RETRIES retry floor — an
    explicit ``--faults`` keeps exactly ``--retries`` so over-budget
    resilience tests stay deterministic even under a chaos-suite env.
    """
    retries = args.retries
    fault_spec = args.faults
    if fault_spec is None:
        fault_spec = env_str("SEQALIGN_FAULTS") or None
        if fault_spec:
            retries = max(retries, env_int("SEQALIGN_FAULT_RETRIES", 0))
    return RetryPolicy(retries=retries), fault_spec


def _build_obs(args) -> tuple[bool, str | None, float | None, str | None]:
    """Resolve the observability plane's configuration.

    Mirrors :func:`_build_policy`: each flag falls back to its declared
    env var.  Any of ``--metrics`` / ``--metrics-out`` / ``--heartbeat``
    / ``--trace-out`` arms the plane — asking for the report, the
    heartbeat that reads it, or the trace timeline IS asking for the
    counters.
    """
    metrics_out = args.metrics_out or env_str("SEQALIGN_METRICS_OUT")
    trace_out = args.trace_out or env_str("SEQALIGN_TRACE")
    heartbeat_s = (
        args.heartbeat
        if args.heartbeat is not None
        else env_float("SEQALIGN_HEARTBEAT_S")
    )
    enabled = bool(
        args.metrics
        or env_flag("SEQALIGN_METRICS")
        or metrics_out
        or heartbeat_s
        or trace_out
    )
    return enabled, metrics_out or None, heartbeat_s, trace_out or None


def _make_degrader(args, scorer) -> BackendDegrader:
    """The run's degradation-chain state (a pass-through unless
    ``--degrade``); replacement scorers keep the original's sharding and
    chunk budget — only the backend changes."""
    return BackendDegrader(
        scorer,
        lambda b: AlignmentScorer(
            backend=b,
            chunk_budget=scorer.chunk_budget,
            sharding=scorer.sharding,
            check=scorer.check,
        ),
        enabled=bool(args.degrade),
    )


def _feature_import(what: str, importer):
    """Import a lazily-loaded subsystem with a clear error if absent."""
    try:
        return importer()
    except ModuleNotFoundError as e:
        raise FeatureUnavailableError(
            f"{what} is not available in this build ({e.name} missing)"
        ) from e


def _build_sharding(mesh_arg: str | None):
    # The grammar lives in parallel.specs (shared with the native ABI's
    # TPU_SEQALIGN_MESH); this wrapper only exists so the CLI's lazy-import
    # policy stays local.
    from ..parallel.specs import build_sharding

    return build_sharding(mesh_arg)


def _make_scorer(args, distributed_active: bool) -> AlignmentScorer:
    """Build the scorer with the shared sharding-default policy: a
    distributed run without an explicit --mesh gets the global batch mesh
    (otherwise every host would redo the full batch — MPI_Scatter
    semantics, main.c:174)."""
    sharding = _build_sharding(args.mesh)
    if sharding is None and distributed_active:

        def _imp_default():
            from ..parallel.sharding import BatchSharding

            return BatchSharding

        sharding = _feature_import(
            "--distributed batch sharding", _imp_default
        ).over_devices(None)
    return AlignmentScorer(
        backend=args.backend,
        sharding=sharding,
        check=bool(args.check) or env_flag("SEQALIGN_CHECK"),
    )


def _prewarm_enabled(args) -> bool:
    return bool(args.prewarm or env_flag("SEQALIGN_PREWARM"))


def _run_prewarm(args, timer, *, problem=None, backend=None) -> bool:
    """Run the AOT warm plane at process start (behind --prewarm /
    SEQALIGN_PREWARM).  Prewarming is an optimization: ANY failure is a
    stderr warning, never a run failure.  Returns True when the prewarm
    actually ran (serve uses it to pin the tick-0 steady baseline)."""
    if not _prewarm_enabled(args):
        return False
    try:
        from ..aot.prewarm import prewarm
        from ..serve.batcher import DEFAULT_BLOCK_ROWS

        with timer.phase("prewarm"):
            # A problem-bearing prewarm also warms the SERVE block
            # shapes this problem's length distribution would produce
            # (manifest forward-coverage: the batch run's manifest is
            # what a later `--serve --prewarm` restart replays).
            prewarm(
                problem=problem,
                backend=backend,
                rows_per_block=(
                    env_int("SEQALIGN_SERVE_BLOCK_ROWS") or DEFAULT_BLOCK_ROWS
                ),
            )
        return True
    except Exception as e:
        # advisory: prewarm is warm-up, not correctness — scoring simply
        # proceeds with cold compiles.
        print(
            f"mpi_openmp_cuda_tpu: warning: prewarm failed ({e})",
            file=sys.stderr,
        )
        return False


def _run_streaming_worker(args, timer: PhaseTimer, dist, policy) -> int:
    """Worker-side --stream --distributed loop: receive the broadcast
    stream header, then score every broadcast chunk inside the same
    collective schedule as the coordinator, until the end sentinel.
    Workers parse nothing, journal nothing, and print nothing."""
    with timer.phase("setup"):
        scorer = _make_scorer(args, True)
    weights, seq1_codes, _ = dist.broadcast_stream_meta(None)
    with timer.phase("stream"):
        # The worker PIPELINES one chunk in flight, mirroring the
        # coordinator's submit(i+1)-then-finish(i) schedule exactly, so
        # the cross-host collective order is identical on every host:
        #   bcast(1) d(1) bcast(2) d(2) gather(1) ... bcast(end) gather(n)
        # (the coordinator broadcasts the end sentinel BEFORE its final
        # gather for the same reason).  A worker that materialised each
        # chunk synchronously would run gather(i) before bcast(i+1) while
        # the coordinator runs them the other way around — two
        # communicating collectives in opposite orders across hosts is a
        # deadlock until the coordination timeout.
        # Retries here (dispatch AND materialise) only help when the
        # failure is JOB-WIDE: every host fails the same stage and
        # re-enters the sharded collectives in lockstep with the
        # coordinator's own _finish retry (whose fallback is the same
        # synchronous rescore as _worker_finish below).  A failure seen
        # by one host alone desynchronises the collective schedules
        # either way and is torn down by the coordination timeout; see
        # the --retries help (ADVICE r2).
        def _worker_finish(pending):
            promise, codes, budget = pending
            policy.materialise(
                promise,
                lambda: scorer.score_codes(seq1_codes, codes, weights),
                "chunk scoring",
                budget,
            )

        pending = None
        while True:
            codes = dist.broadcast_chunk(None)
            if codes is None:
                break
            cur = None
            if codes:
                budget = policy.new_budget()
                promise = policy.run(
                    lambda: scorer.score_codes_async(
                        seq1_codes, codes, weights
                    ),
                    "chunk dispatch",
                    budget=budget,
                )
                cur = (promise, codes, budget)
            if pending is not None:
                _worker_finish(pending)
            pending = cur
        if pending is not None:
            _worker_finish(pending)
    timer.report()
    return 0


def _run_streaming(
    args,
    timer: PhaseTimer,
    policy: RetryPolicy,
    dist=None,
    coordinator=True,
    out_stream=None,
) -> int:
    """The --stream pipeline: parse and score CHUNK sequences at a time
    with a window of chunks in flight on the device (single-process
    default 4, TPU_SEQALIGN_STREAM_DEPTH; multi-host exactly 1 — the
    worker mirrors that schedule collective-for-collective).

    While earlier chunks compute (JAX dispatch is asynchronous, and each
    pending's device->host copy is prefetched at dispatch), the host
    parses and submits later chunks, materialising the oldest only once
    the window is full — the host-IO / device-compute overlap tier
    (SURVEY §2.4 PP row; r5 measurement + the tunnelled-link rationale
    in BASELINE.md "Streaming e2e measured").  Host memory is bounded by
    (window+1) chunks (plus one ~30-byte line per result).
    Formatted output is buffered and flushed only after the whole stream
    succeeds, preserving the fail-stop contract: a truncated or invalid
    batch emits nothing on stdout, exactly like the non-streaming path.

    With --journal, a StreamJournal composes resume with the bounded
    memory: the header fingerprints (weights, Seq1, N) and every record
    carries a per-sequence content hash, so a preempted run rescores only
    the sequences the journal has no (hash-matching) entry for.

    With --distributed, only the coordinator reads stdin: it broadcasts
    the stream header once and then each (journal-reduced) chunk before
    dispatching it, so every host scores the identical chunk inside the
    same collectives while keeping host memory bounded on all of them;
    workers run :func:`_run_streaming_worker`.  Any coordinator-side
    failure mid-stream broadcasts an abort so workers exit instead of
    blocking on the next chunk.
    """
    import contextlib
    import io

    import numpy as np

    from .parse import open_input, parse_stream_header

    multi = dist is not None and dist.process_count() > 1
    if multi and not coordinator:
        return _run_streaming_worker(args, timer, dist, policy)

    with timer.phase("setup"):
        # All scoring below goes through deg.scorer: a mid-stream
        # degradation replaces the scorer for every later chunk too.
        deg = _make_degrader(args, _make_scorer(args, dist is not None))
    obs_gauge("backend", deg.scorer.backend)

    all_results = [] if args.json else None
    lines = io.StringIO()

    # Every coordinator-side failure window must broadcast an abort at the
    # collective the workers are currently blocked on, or they hang until
    # the coordination-service timeout instead of failing promptly:
    # before/at the header parse -> workers wait in broadcast_stream_meta;
    # after it (journal load, chunk loop) -> they wait in broadcast_chunk.
    try:
        stream_cm = open_input(args.input)
    except Exception:
        if multi:
            dist.broadcast_stream_meta(None, failed=True)
        raise
    with stream_cm as stream:
        with timer.phase("parse_header"):
            try:
                header = parse_stream_header(stream)
            except Exception:
                if multi:
                    dist.broadcast_stream_meta(None, failed=True)
                raise
        if multi:
            dist.broadcast_stream_meta(
                (header.weights, header.seq1_codes, header.num_seq2)
            )
        # Denominator for the heartbeat's "chunk I/N" and the run report.
        obs_gauge("chunks_total", -(-header.num_seq2 // args.stream))
        journal, seq_hash, mismatch_error, done = None, None, None, {}
        if args.journal:
            try:
                _check_resume(args)

                def _imp():
                    from ..utils.journal import (
                        JournalMismatchError,
                        StreamJournal,
                        seq_hash,
                    )

                    return StreamJournal, seq_hash, JournalMismatchError

                StreamJournal, seq_hash, mismatch_error = _feature_import(
                    "--journal resume", _imp
                )
                journal = StreamJournal(
                    args.journal,
                    header.weights,
                    header.seq1_codes,
                    header.num_seq2,
                )
                done = journal.load()
            except BaseException:
                if multi:
                    dist.broadcast_chunk(None, failed=True)
                raise

        # Dispatch/materialise (shared budget, --degrade chain, oracle
        # re-verification) live in io.pipeline, shared with --serve.
        pipe = ChunkPipeline(policy, deg)
        # Feed overlap (r6): a one-chunk lookahead below stages chunk
        # N+1's host->device transfers while chunk N computes.  Off on
        # multi-host (the per-chunk collective order is the schedule;
        # no speculative device traffic) and under --resume (the
        # journal reduces each chunk to its missing subset, so a
        # full-chunk prestage would mostly move dead bytes).
        stager = FeedStager(
            deg, enabled=False if (multi or journal is not None) else None
        )

        def _submit(start, codes, staged=None):
            """Dispatch a chunk; returns (promise, start, codes, pend, rows,
            hashes, budget).  pend is None without a journal (whole chunk
            scored); with one, only hash-missing sequences are dispatched
            and rows pre-holds the journalled results.  budget is the
            chunk's shared retry counter: dispatch and materialise together
            get args.retries retries, like the batch path.  ``staged`` is
            the chunk's prestaged feed handle (or None): advisory and
            single-use, see ChunkPipeline.dispatch."""
            budget = policy.new_budget()
            if journal is None:
                if multi:
                    # Workers must see the identical chunk before the
                    # sharded dispatch's collectives.
                    dist.broadcast_chunk(codes)
                promise = pipe.dispatch(
                    header.seq1_codes,
                    codes,
                    header.weights,
                    budget,
                    staged=staged,
                )
                return (promise, start, codes, None, None, None, budget)
            hashes = [seq_hash(c) for c in codes]
            pend = []
            rows = np.zeros((len(codes), 3), dtype=np.int32)
            for j, h in enumerate(hashes):
                rec = done.get(start + j)
                if rec is not None and rec[0] == h:
                    rows[j] = rec[1]
                elif rec is not None:
                    raise mismatch_error(
                        f"journal entry for sequence {start + j} does not "
                        "match the input (sequence changed); delete the "
                        "journal or pass a fresh --journal path"
                    )
                else:
                    pend.append(j)
            promise = None
            if multi:
                # The journal-REDUCED chunk is the schedule: broadcast it
                # even when empty so the workers' chunk loop stays in
                # lockstep (they skip scoring an empty chunk, as here).
                dist.broadcast_chunk([codes[j] for j in pend])
            if pend:
                promise = pipe.dispatch(
                    header.seq1_codes,
                    [codes[j] for j in pend],
                    header.weights,
                    budget,
                    staged=staged,
                )
            return (promise, start, codes, pend, rows, hashes, budget)

        def _finish(promise, start, codes, pend, rows, hashes, budget):
            res = None
            if promise is not None:
                sub = codes if pend is None else [codes[j] for j in pend]
                res = pipe.materialise(
                    promise, header.seq1_codes, sub, header.weights, budget
                )
            if pend is None:
                out = res
            else:
                out = rows
                if res is not None:
                    for j, row in zip(pend, res):
                        out[j] = row
                    # Retrying an append is safe: an injected fault fires
                    # before the first byte, and a partially-flushed real
                    # failure at worst duplicates records (same key, same
                    # values — the resume reader keeps the last).  The
                    # append gets its own fresh budget so journal IO
                    # faults cannot eat a chunk's scoring budget.
                    policy.run(
                        lambda: journal.append(
                            [start + j for j in pend],
                            [hashes[j] for j in pend],
                            res,
                        ),
                        "journal append",
                    )
            print_results(out, out=lines, start=start)
            if all_results is not None:
                all_results.extend(out)

        with contextlib.ExitStack() as stack:
            try:
                # Context ENTRY failures (journal file unwritable, bad
                # --trace dir) are coordinator-side failure windows too:
                # they must abort workers, so they enter via the stack
                # inside this guarded block rather than a `with` header.
                stack.enter_context(timer.phase("stream"))
                stack.enter_context(device_trace(args.trace))
                if journal is not None:
                    stack.enter_context(journal)
                # In-flight window (io.pipeline.PendingWindow, shared
                # with --serve).  Multi-host: EXACTLY one chunk, the
                # schedule _run_streaming_worker mirrors collective-for-
                # collective.  Single-process: a deeper window (default
                # 4, env-tunable) — on a tunnelled TPU each result fetch
                # costs a ~0.1 s link round trip, and with one chunk in
                # flight those round trips serialise the whole pipeline
                # (measured 6.3x over batch mode at 8 chunks, r5);
                # prefetch() starts every chunk's device->host copy at
                # dispatch, and the window gives the copies time to land
                # before _finish needs them.  Host memory stays bounded:
                # window+1 chunks of codes plus the output lines.
                window = PendingWindow(
                    1
                    if multi
                    else max(1, env_int("TPU_SEQALIGN_STREAM_DEPTH", 4)),
                    _finish,
                )
                end_sent = False
                drained_at = None
                # One-chunk input lookahead: each iteration dispatches
                # the HELD chunk, then stages the just-read chunk's
                # host->device transfers (FeedStager — a no-op handle on
                # multi/--resume) so they overlap the held chunk's
                # compute, then lets the window finish the oldest entry.
                pending_input = None
                for start, codes in header.iter_chunks(args.stream):
                    if drain_requested():
                        # Preemption drain: stop ADMITTING chunks; the
                        # in-flight window below still materialises (and
                        # journals) normally, then the run exits 75.  A
                        # held-but-undispatched lookahead chunk is NOT
                        # admitted: the drain point is ITS start.
                        if pending_input is not None:
                            drained_at = pending_input[0]
                            pending_input = None
                        else:
                            drained_at = start
                        break
                    if pending_input is None:
                        pending_input = (
                            start,
                            codes,
                            stager.stage(
                                header.seq1_codes, codes, header.weights
                            ),
                        )
                        continue
                    item = _submit(*pending_input)
                    pending_input = (
                        start,
                        codes,
                        stager.stage(
                            header.seq1_codes, codes, header.weights
                        ),
                    )
                    window.push(*item)
                if pending_input is not None:
                    window.push(*_submit(*pending_input))
                    pending_input = None
                if multi:
                    # End sentinel BEFORE the final materialise: the
                    # pipelined worker mirrors this exactly (it learns
                    # the stream ended, then gathers its last in-flight
                    # chunk), keeping the cross-host collective order
                    # identical on every host — see _run_streaming_worker.
                    dist.broadcast_chunk(None, end=True)
                    end_sent = True
                window.flush()
                if drained_at is not None:
                    # Drained: in-flight chunks are journalled (fsync'd on
                    # append) but NOTHING goes to stdout — the fail-stop
                    # contract holds, and on multi-host the end sentinel
                    # above already released the workers cleanly.
                    if journal is not None:
                        journal.append_event("drain")
                        raise DrainInterrupt(
                            f"stream preempted before sequence {drained_at}"
                            " of "
                            f"{header.num_seq2}; scored chunks are in the "
                            "journal — rerun with --resume to finish"
                        )
                    raise DrainInterrupt(
                        f"stream preempted before sequence {drained_at} of "
                        f"{header.num_seq2}; no --journal, so a rerun "
                        "starts over"
                    )
            except BaseException:
                if multi and not end_sent:
                    # Any coordinator-side failure (parse, journal
                    # mismatch, scoring) must release workers blocked on
                    # the next chunk broadcast — whole-job fail-stop.
                    # (After the end sentinel the workers are already
                    # released; a failure in the final materialise
                    # surfaces on every host through the computation
                    # itself.)
                    dist.broadcast_chunk(None, failed=True)
                raise
    (out_stream or sys.stdout).write(lines.getvalue())
    if args.json:
        write_json_sidecar(
            all_results, args.json, meta={"backend": deg.scorer.backend}
        )
    timer.report()
    return 0


def run(argv: list[str] | None = None) -> int:
    from ..utils.platform import (
        apply_platform_override,
        enable_compilation_cache,
    )

    apply_platform_override()
    enable_compilation_cache()
    args = build_arg_parser().parse_args(argv)
    # Static argument-compatibility checks: fail before any expensive phase
    # (a multi-host job should not complete init + broadcast just to learn
    # its flags conflict).
    def _reject_combos(base: str, pairs) -> bool:
        for flag, bad, why in pairs:
            if bad:
                print(
                    f"mpi_openmp_cuda_tpu: error: {flag} cannot be combined "
                    f"with {base} ({why})",
                    file=sys.stderr,
                )
                return True
        return False

    if args.stream and _reject_combos("--stream", (
        ("--selfcheck", args.selfcheck, "selfcheck re-verifies against "
         "the fully-materialised problem"),
    )):
        return EX_USAGE
    if args.degrade and _reject_combos("--degrade", (
        ("--distributed", args.distributed, "the backend choice is the "
         "SPMD program itself; a lone host degrading its backend "
         "desynchronises the collective schedules"),
    )):
        return EX_USAGE
    if args.serve and _reject_combos("--serve", (
        ("--stream", args.stream is not None, "the serve loop IS the "
         "streaming pipeline; chunking is driven by the request queue, "
         "not a flag"),
        ("--selfcheck", args.selfcheck, "selfcheck re-verifies a "
         "fully-materialised batch; a server has no final batch"),
        ("--distributed", args.distributed, "the serving plane is "
         "single-process; shard the scorer with --mesh instead"),
    )):
        return EX_USAGE
    if args.fleet_worker and _reject_combos("--fleet-worker", (
        ("--serve", args.serve, "a process is the fleet coordinator OR "
         "a scoring worker, never both"),
        ("--stream", args.stream is not None, "workers score fleet "
         "superblocks claimed off the board, not streamed chunks"),
        ("--distributed", args.distributed, "the fleet is its own "
         "multi-process layer on the coordination board"),
        ("--port", args.port is not None, "workers take work from the "
         "board, not a socket"),
    )):
        return EX_USAGE
    if args.fleet_worker and not args.fleet_board:
        print(
            "mpi_openmp_cuda_tpu: error: --fleet-worker requires "
            "--fleet-board DIR (the board is where work is claimed)",
            file=sys.stderr,
        )
        return EX_USAGE
    if args.fleet_standby and _reject_combos("--fleet-standby", (
        ("--serve", args.serve, "a standby IS a serve loop in waiting; "
         "it becomes the coordinator only by winning the takeover"),
        ("--fleet-worker", args.fleet_worker, "a process is a standby "
         "coordinator OR a scoring worker, never both"),
        ("--stream", args.stream is not None, "the standby serves fleet "
         "requests after takeover, not streamed chunks"),
        ("--distributed", args.distributed, "the fleet is its own "
         "multi-process layer on the coordination board"),
        ("--input", args.input is not None, "a standby's requests come "
         "from the dead leader's checkpoint and reconnecting clients, "
         "not a pipe"),
    )):
        return EX_USAGE
    if args.fleet_standby and not args.fleet_board:
        print(
            "mpi_openmp_cuda_tpu: error: --fleet-standby requires "
            "--fleet-board DIR (the board is where the leader lease "
            "lives)",
            file=sys.stderr,
        )
        return EX_USAGE
    if args.fleet_board and not (
        args.serve or args.fleet_worker or args.fleet_standby
    ):
        print(
            "mpi_openmp_cuda_tpu: error: --fleet-board requires --serve "
            "(coordinator), --fleet-worker (scoring worker), or "
            "--fleet-standby (failover coordinator)",
            file=sys.stderr,
        )
        return EX_USAGE
    if args.port is not None and not (args.serve or args.fleet_standby):
        print(
            "mpi_openmp_cuda_tpu: error: --port requires --serve (the "
            "port is where the serving loop listens)",
            file=sys.stderr,
        )
        return EX_USAGE
    if args.telemetry_port is not None and not (
        args.serve or args.fleet_standby
    ):
        print(
            "mpi_openmp_cuda_tpu: error: --telemetry-port requires "
            "--serve (live telemetry scrapes a running serve loop; a "
            "batch run's report is --metrics-out)",
            file=sys.stderr,
        )
        return EX_USAGE
    if args.resume and not args.journal:
        print(
            "mpi_openmp_cuda_tpu: error: --resume requires --journal PATH "
            "(the journal is what a resume resumes from)",
            file=sys.stderr,
        )
        return EX_USAGE

    # A malformed --faults spec (unknown site, bad grammar) is a usage
    # error like any other bad flag value: validate it HERE, before the
    # broad runtime try below would translate the ValueError into 65.
    try:
        policy, fault_spec = _build_policy(args)
        if fault_spec:
            parse_spec(fault_spec)
    except ValueError as e:
        print(f"mpi_openmp_cuda_tpu: error: {e}", file=sys.stderr)
        return EX_USAGE

    guard = None
    out_stream = None  # None -> sys.stdout

    def _close_guard(suppress: bool) -> None:
        nonlocal guard
        if guard is None:
            return
        closing, guard = guard, None
        try:
            closing.__exit__(None, None, None)
        except OSError:
            if not suppress:
                raise

    _drain = None
    registry = recorder = None
    metrics_out = None
    trace_out = None
    prev_usr2 = None
    rc: int | None = None
    try:
        # The observability plane arms before anything that can publish
        # into it (faults, watchdog, scoring); the finally below flushes
        # the run report on EVERY exit path, 65 and 75 included.
        # --serve arms it unconditionally: the flight recorder must be
        # taping before the first request so a later wedge has history.
        obs_on, metrics_out, heartbeat_s, trace_out = _build_obs(args)
        if obs_on or args.serve or args.fleet_standby or args.fleet_worker:
            # A --fleet-worker always arms trace + flightrec: its board
            # snapshots (metrics, recent trace events, the tape the
            # coordinator collects post-mortem) need armed planes to
            # have any content.
            registry, recorder = arm_observability(
                with_trace=bool(trace_out) or bool(args.fleet_worker),
                flightrec_depth=(
                    env_int("SEQALIGN_FLIGHTREC_DEPTH", 256)
                    if (
                        args.serve
                        or args.fleet_standby
                        or args.fleet_worker
                        or obs_on
                    )
                    else 0
                ),
            )
            try:
                # Live triage: SIGUSR2 dumps the flight recorder without
                # disturbing the run (restored in the finally below).
                prev_usr2 = signal.signal(signal.SIGUSR2, _sigusr2_dump)
            except (ValueError, AttributeError, OSError):
                # Non-main thread, or a platform without SIGUSR2.
                prev_usr2 = None
        # The --profile timer shares the armed span recorder, so profile
        # phases and the run report's span section are one measurement.
        timer = PhaseTimer(enabled=args.profile, recorder=recorder)
        activate_faults(fault_spec)
        deadline = (
            args.deadline
            if args.deadline is not None
            else env_float("SEQALIGN_DEADLINE_S")
        ) or None
        if deadline or heartbeat_s:
            # Heartbeat-only (deadline None) is legal: the monitor thread
            # then enforces nothing and only emits the status line.
            activate_watchdog(
                deadline,
                heartbeat_s=heartbeat_s,
                heartbeat=(
                    obs_export.heartbeat_callback() if heartbeat_s else None
                ),
            )
        # Preemption drain: SIGTERM/SIGINT (or a pre-armed SEQALIGN_DRAIN)
        # finishes in-flight chunks, flushes the journal, and exits 75.
        # Armed for the whole run, disarmed (handlers restored) in the
        # finally below so library callers never inherit our handlers.
        _drain = drain_guard()
        _drain.__enter__()
        if args.fleet_worker:

            def _imp_fleet():
                from ..serve import fleet as fleet_mod

                return fleet_mod

            fleet_mod = _feature_import(
                "--fleet-worker scoring loop", _imp_fleet
            )
            with timer.phase("setup"):
                deg = _make_degrader(args, _make_scorer(args, False))
            obs_gauge("backend", deg.scorer.backend)
            # A joining worker prewarms from the shipped AOT manifest so
            # it claims its first superblock with warm jit caches.
            _run_prewarm(args, timer, backend=deg.scorer.backend)
            rc = fleet_mod.run_fleet_worker(args, timer, policy, deg)
            return rc
        if args.serve or args.fleet_standby:
            if args.journal:
                _check_resume(args)

            def _imp_serve():
                from ..serve import loop as serve_loop

                return serve_loop

            serve_mod = _feature_import("--serve serving loop", _imp_serve)
            with timer.phase("setup"):
                # The serving loop's whole value is this scorer living
                # across requests: its jit caches stay warm for every
                # superblock shape seen so far.
                deg = _make_degrader(args, _make_scorer(args, False))
            obs_gauge("backend", deg.scorer.backend)
            # Serve prewarm is manifest replay: the shapes a fresh
            # replica must answer warm are whatever a prior process
            # (batch or serve) recorded.  When it ran, the loop pins its
            # steady-compile baseline at tick 0.
            prewarmed = _run_prewarm(args, timer, backend=deg.scorer.backend)
            rc = serve_mod.run_serve(
                args, timer, policy, deg, out_stream=out_stream,
                prewarmed=prewarmed,
            )
            return rc
        coordinator = True
        dist = None
        if args.distributed:
            # Collective backends may write banners straight to fd 1 from
            # C++ (Gloo does on CPU); guard the byte-exact result stream
            # for the whole run and print results to the true stdout only.
            # The guard must be in place before distributed init starts
            # emitting that chatter.
            guard = guarded_stdout()
            out_stream = guard.__enter__()
            with timer.phase("distributed_init"):

                def _imp():
                    from ..parallel import distributed

                    return distributed

                dist = _feature_import("--distributed multi-host init", _imp)
                dist.initialize_distributed()
                coordinator = dist.is_coordinator()
        if args.stream:
            if not args.distributed:
                # Replay-only (no materialised problem before the stream
                # starts): a drain -> --resume rerun rejoins warm from
                # its predecessor's manifest.
                _run_prewarm(args, timer)
            rc = _run_streaming(
                args,
                timer,
                policy,
                dist=dist,
                coordinator=coordinator,
                out_stream=out_stream,
            )
            _close_guard(suppress=False)
            return rc
        with timer.phase("parse"):
            # Only the coordinator touches stdin (reference ROOT semantics);
            # workers receive the parsed problem via broadcast.
            problem = None
            if coordinator:
                try:
                    problem = load_problem(args.input)
                except Exception:
                    if args.distributed:
                        # Tell workers to abort instead of hanging in the
                        # broadcast collective (whole-job fail-stop).
                        dist.broadcast_problem(None, failed=True)
                    raise
            if args.distributed:
                problem = dist.broadcast_problem(problem)
        with timer.phase("setup"):
            # Scoring goes through deg.scorer so a --degrade fallback
            # replaces the backend for the retry that follows it.
            deg = _make_degrader(args, _make_scorer(args, args.distributed))
        obs_gauge("backend", deg.scorer.backend)
        if not args.distributed and deg.scorer.sharding is None:
            # Batch prewarm gets the problem: the warm set mirrors the
            # LOCAL dispatch routing, so sharded/multi-host runs (whose
            # programs are per-device) stay replay-free here.
            _run_prewarm(
                args, timer, problem=problem, backend=deg.scorer.backend
            )
        journal, done = None, None
        if args.journal:

            def _imp():
                from ..utils.journal import ResultJournal

                return ResultJournal

            journal = _feature_import("--journal resume", _imp)(args.journal)
            if args.distributed and dist.process_count() > 1:
                # Resume composes with multi-host by making the reduced
                # schedule a broadcast fact: the coordinator loads its
                # journal's done-set and every host derives the identical
                # pending list + chunking, so the collective schedules
                # cannot diverge.  Only the coordinator touches the file
                # (so only it can assert --resume's file-exists contract).
                if coordinator:
                    try:
                        _check_resume(args)
                        done = journal.load_done(problem)
                    except Exception:
                        dist.broadcast_index_set(None, failed=True)
                        raise
                    dist.broadcast_index_set(sorted(done))
                else:
                    done = {
                        int(i): None for i in dist.broadcast_index_set(None)
                    }
            else:
                _check_resume(args)

        # Feed overlap (r6), batch tier: start the whole batch's
        # host->device transfers (async device_put, one handle per
        # launch group) before the scoring phase opens.  Local
        # non-resume runs only — multi-host stages per-shard inside the
        # sharded path, and --resume's reduced schedule plans different
        # shapes.  Single-use: the primary attempt drains the handle,
        # retries and the degraded chain re-stage from host.
        batch_staged = None
        if not (args.distributed and dist.process_count() > 1):
            if journal is None:
                batch_staged = FeedStager(deg).stage(
                    problem.seq1_codes, problem.seq2_codes, problem.weights
                )

        def _score_once(sc, staged=None):
            if journal is not None:
                # Workers run the identical reduced schedule without
                # touching any journal file (record=False).
                return journal.score_with_resume(
                    sc, problem, done=done, record=coordinator
                )
            if staged is not None and hasattr(sc, "prestage_codes"):
                return sc.score_codes(
                    problem.seq1_codes,
                    problem.seq2_codes,
                    problem.weights,
                    staged=staged,
                )
            return sc.score_codes(
                problem.seq1_codes, problem.seq2_codes, problem.weights
            )

        def _batch_verify(rows):
            # First degraded result only: resumed journal rows hold the
            # pre-fault backend's (correct) values, so a whole-batch
            # prefix check stays valid under --journal too.
            verify_rows_against_oracle(
                problem.seq1_codes, problem.seq2_codes, problem.weights, rows
            )

        beacon_s = env_float("SEQALIGN_BEACON_S")
        with timer.phase("score"), device_trace(args.trace):
            if args.distributed and beacon_s and not args.journal:
                # Lost-shard rescue tier: trade the SPMD collective gather
                # (where one dead worker hangs every peer) for per-process
                # local shards posted to the coordination-service board; a
                # worker that misses the beacon deadline has its index-set
                # rescored locally on the coordinator.  --journal takes
                # precedence (its resume schedule IS the collective
                # schedule); workers return None and print nothing.
                results = dist.scatter_gather_rescue(
                    problem.seq1_codes,
                    problem.seq2_codes,
                    problem.weights,
                    policy=policy,
                    beacon_s=beacon_s,
                    backend=args.backend,
                )
            else:
                results = run_degrading(
                    policy,
                    deg,
                    lambda: _score_once(deg.scorer, batch_staged),
                    _score_once,
                    "scoring",
                    verify=_batch_verify if deg.enabled else None,
                )
        # Coordinator-only: one host's oracle re-verification suffices,
        # and under --journal workers hold schedule placeholders (zeros)
        # for resumed rows, not results.
        if args.selfcheck and coordinator:
            with timer.phase("selfcheck"):

                def _imp_check():
                    from ..utils.selfcheck import verify_results

                    return verify_results

                checked = _feature_import("--selfcheck validation", _imp_check)(
                    problem, results
                )
                print(
                    f"mpi_openmp_cuda_tpu: selfcheck OK "
                    f"({checked} sequences re-verified on the host oracle)",
                    file=sys.stderr,
                )
        with timer.phase("print"):
            if coordinator:  # workers print nothing (main.c:199-211 semantics)
                print_results(results, out=out_stream)
                if args.json:
                    write_json_sidecar(
                        results,
                        args.json,
                        meta={"backend": deg.scorer.backend},
                    )
        timer.report()
        # Close the guard while still inside the try: the final flush of
        # buffered results can itself raise (e.g. BrokenPipeError under
        # `... | head`), and must hit the handlers below.
        _close_guard(suppress=False)
        rc = EX_OK
        return rc
    except DrainInterrupt as e:
        # A requested preemption, not a failure: nothing was printed
        # (fail-stop stdout), everything scored so far is fsync'd in the
        # journal, and 75 tells the supervisor a rerun will finish the job.
        print(f"mpi_openmp_cuda_tpu: drained: {e}", file=sys.stderr)
        rc = EX_TEMPFAIL
        return rc
    except BrokenPipeError:
        rc = 1
        return rc
    except Exception as e:  # fail-stop: diagnose on stderr, nonzero exit (C11)
        print(f"mpi_openmp_cuda_tpu: error: {e}", file=sys.stderr)
        rc = EX_TEMPFAIL if _is_resumable(e) else EX_FATAL
        return rc
    finally:
        # Report flush comes FIRST, while the run's exit code is known and
        # before the plane disarms: a failed (65) or preempted (75) run
        # still leaves its report behind — often the only evidence of what
        # the retries and degradations did.  A flush failure warns on
        # stderr; it must never mask the run's own verdict.
        if registry is not None:
            # A fatal exit is a dump trigger like watchdog expiry or a
            # breaker open: the last N bus events are often the only
            # context a crashed serve replica leaves behind.
            if rc == EX_FATAL:
                obs_flightrec.dump_active("fatal-exit")
            tracer = obs_trace.active_trace()
            try:
                obs_export.flush_trace(tracer, trace_out, exit_code=rc)
            except Exception as flush_err:  # pragma: no cover - FS-dependent
                # advisory: a failed trace flush must never mask the
                # run's own verdict.
                print(
                    "mpi_openmp_cuda_tpu: warning: trace not written "
                    f"({flush_err})",
                    file=sys.stderr,
                )
            try:
                obs_export.flush_run_report(
                    registry,
                    recorder,
                    metrics_out,
                    exit_code=rc,
                    extra=(
                        {"gap_attribution": tracer.gap_attribution()}
                        if tracer is not None
                        else None
                    ),
                )
            except Exception as flush_err:  # pragma: no cover - FS-dependent
                # advisory: a failed report flush must never mask the
                # run's own verdict.
                print(
                    "mpi_openmp_cuda_tpu: warning: run report not written "
                    f"({flush_err})",
                    file=sys.stderr,
                )
            if prev_usr2 is not None:
                try:
                    signal.signal(signal.SIGUSR2, prev_usr2)
                except (ValueError, OSError):  # pragma: no cover
                    pass
            disarm_observability()
        # Error paths: restore fd 1 without letting a secondary flush
        # failure mask the original exception.  Faults/watchdog/drain are
        # armed per run: disarm (and join the watchdog thread, restore the
        # signal handlers) so library callers after a CLI run see no
        # ambient runtime.
        deactivate_faults()
        deactivate_watchdog()
        if _drain is not None:
            _drain.__exit__(None, None, None)
        _close_guard(suppress=True)


def main() -> None:
    try:
        rc = run()
    except (KeyError, ValueError) as e:
        # Only the pre-arm plumbing can get here (a mis-declared env read
        # in utils.platform, a malformed env value): run()'s ladder maps
        # everything after the flush try is entered.  Usage-class verdict
        # with the actionable message, not a traceback.
        print(f"mpi_openmp_cuda_tpu: usage: {e}", file=sys.stderr)
        rc = EX_USAGE
    sys.exit(rc)
