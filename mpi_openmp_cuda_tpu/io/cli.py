"""CLI driver (reference parity: L4/L5 orchestration, main.c main()).

``python -m mpi_openmp_cuda_tpu < input.txt`` reproduces the reference's
``mpiexec -np 2 ./final < input.txt`` contract: results on stdout in the
exact ``#i: score: S, n: N, k: K`` format, diagnostics on stderr, non-zero
exit on any failure (the C11 fail-stop stance).  Optional flags extend the
contract without breaking it (SURVEY §5 config tier).
"""

from __future__ import annotations

import argparse
import sys

from ..ops.dispatch import AlignmentScorer
from ..utils.profiling import PhaseTimer, device_trace
from .parse import load_problem
from .printer import print_results, write_json_sidecar


def _nonnegative_int(text: str) -> int:
    value = int(text)
    if value < 0:
        raise argparse.ArgumentTypeError(f"must be >= 0, got {value}")
    return value


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_openmp_cuda_tpu",
        description="TPU-native batch sequence-alignment scorer "
        "(stdin/stdout contract of the MPI+OpenMP+CUDA reference).",
    )
    p.add_argument(
        "--input",
        default=None,
        help="input file (default: stdin, like the reference's './final < input.txt')",
    )
    p.add_argument(
        "--backend",
        choices=("xla", "xla-gather", "pallas", "oracle"),
        default="xla",
        help="compute path: pure-XLA MXU formulation (default), gather "
        "formulation, Pallas TPU kernel, or host numpy oracle",
    )
    p.add_argument(
        "--mesh",
        default=None,
        help="device mesh: 'N' or 'batch:N' shards the Seq2 batch over N "
        "devices (data parallel); 'seq:N' ring-shards Seq1 over N devices "
        "(sequence/context parallel); 'DxS' composes both on a 2-D mesh "
        "(default: no sharding, single device)",
    )
    p.add_argument(
        "--distributed",
        action="store_true",
        help="call jax.distributed.initialize() first (multi-host, the runOn2 analogue)",
    )
    p.add_argument(
        "--json",
        default=None,
        metavar="PATH",
        help="also write results as a JSON sidecar file",
    )
    p.add_argument(
        "--journal",
        default=None,
        metavar="PATH",
        help="per-sequence result journal enabling resume after preemption",
    )
    p.add_argument(
        "--profile",
        action="store_true",
        help="print per-phase wall-clock timings to stderr",
    )
    p.add_argument(
        "--trace",
        default=None,
        metavar="DIR",
        help="capture a jax.profiler device trace of the scoring phase "
        "into DIR (view with TensorBoard / xprof)",
    )
    p.add_argument(
        "--selfcheck",
        action="store_true",
        help="after scoring, rescore a deterministic sample on the host "
        "oracle and fail on any mismatch (sanitizer analogue)",
    )
    p.add_argument(
        "--retries",
        type=_nonnegative_int,
        default=0,
        metavar="N",
        help="retry the scoring phase up to N times on transient device "
        "failure (combine with --journal to resume mid-batch)",
    )
    return p


class FeatureUnavailableError(RuntimeError):
    pass


def _feature_import(what: str, importer):
    """Import a lazily-loaded subsystem with a clear error if absent."""
    try:
        return importer()
    except ModuleNotFoundError as e:
        raise FeatureUnavailableError(
            f"{what} is not available in this build ({e.name} missing)"
        ) from e


def _build_sharding(mesh_arg: str | None):
    if mesh_arg is None:
        return None

    def _imp_batch():
        from ..parallel.sharding import BatchSharding

        return BatchSharding

    def _imp_ring():
        from ..parallel.ring import RingSharding

        return RingSharding

    spec = mesh_arg.split(":")
    if spec[0] == "seq":
        return _feature_import("--mesh sequence sharding", _imp_ring).over_devices(
            seq=int(spec[-1])
        )
    if spec[0] == "batch" or len(spec) > 1:
        # An explicit 'batch:' prefix always means 1-D batch sharding —
        # 'batch:2x4' is a spec error, not a silent 2-D ring mesh.
        return _feature_import("--mesh batch sharding", _imp_batch).over_devices(
            int(spec[-1])
        )
    if "x" in spec[0]:
        dp, sp = (int(t) for t in spec[0].split("x"))
        return _feature_import("--mesh 2-D sharding", _imp_ring).over_devices(
            seq=sp, batch=dp
        )
    return _feature_import("--mesh batch sharding", _imp_batch).over_devices(
        int(spec[0])
    )


def run(argv: list[str] | None = None) -> int:
    from ..utils.platform import apply_platform_override

    apply_platform_override()
    args = build_arg_parser().parse_args(argv)
    timer = PhaseTimer(enabled=args.profile)
    try:
        coordinator = True
        if args.distributed:
            with timer.phase("distributed_init"):

                def _imp():
                    from ..parallel import distributed

                    return distributed

                dist = _feature_import("--distributed multi-host init", _imp)
                dist.initialize_distributed()
                coordinator = dist.is_coordinator()
        with timer.phase("parse"):
            # Only the coordinator touches stdin (reference ROOT semantics);
            # workers receive the parsed problem via broadcast.
            problem = None
            if coordinator:
                try:
                    problem = load_problem(args.input)
                except Exception:
                    if args.distributed:
                        # Tell workers to abort instead of hanging in the
                        # broadcast collective (whole-job fail-stop).
                        dist.broadcast_problem(None, failed=True)
                    raise
            if args.distributed:
                problem = dist.broadcast_problem(problem)
        with timer.phase("setup"):
            sharding = _build_sharding(args.mesh)
            if sharding is None and args.distributed:
                # Distributed without an explicit mesh would make every host
                # redo the full batch; default to the global mesh so the
                # work actually splits (the MPI_Scatter semantics).
                def _imp_default():
                    from ..parallel.sharding import BatchSharding

                    return BatchSharding

                sharding = _feature_import(
                    "--distributed batch sharding", _imp_default
                ).over_devices(None)
            scorer = AlignmentScorer(backend=args.backend, sharding=sharding)
        journal = None
        if args.journal and args.distributed:
            # Resume would make the coordinator score a subset while workers
            # score the full batch — mismatched collectives hang the job.
            raise ValueError("--journal cannot be combined with --distributed")
        if args.journal:

            def _imp():
                from ..utils.journal import ResultJournal

                return ResultJournal

            journal = _feature_import("--journal resume", _imp)(args.journal)
        if args.retries and args.distributed:
            # A retry loop on one host would rerun collectives the other
            # hosts never re-enter; restart the whole job instead.
            raise ValueError("--retries cannot be combined with --distributed")

        def _score_once():
            if journal is not None:
                return journal.score_with_resume(scorer, problem)
            return scorer.score_codes(
                problem.seq1_codes, problem.seq2_codes, problem.weights
            )

        with timer.phase("score"), device_trace(args.trace):
            for attempt in range(args.retries + 1):
                try:
                    results = _score_once()
                    break
                except (ValueError, TypeError):
                    raise  # programming/shape errors are not transient
                except Exception as e:
                    if attempt >= args.retries:
                        raise
                    print(
                        f"mpi_openmp_cuda_tpu: scoring attempt "
                        f"{attempt + 1} failed ({e}); retrying",
                        file=sys.stderr,
                    )
        if args.selfcheck:
            with timer.phase("selfcheck"):

                def _imp_check():
                    from ..utils.selfcheck import verify_results

                    return verify_results

                checked = _feature_import("--selfcheck validation", _imp_check)(
                    problem, results
                )
                print(
                    f"mpi_openmp_cuda_tpu: selfcheck OK "
                    f"({checked} sequences re-verified on the host oracle)",
                    file=sys.stderr,
                )
        with timer.phase("print"):
            if coordinator:  # workers print nothing (main.c:199-211 semantics)
                print_results(results)
                if args.json:
                    write_json_sidecar(
                        results, args.json, meta={"backend": args.backend}
                    )
        timer.report()
        return 0
    except BrokenPipeError:
        return 1
    except Exception as e:  # fail-stop: diagnose on stderr, nonzero exit (C11)
        print(f"mpi_openmp_cuda_tpu: error: {e}", file=sys.stderr)
        return 1


def main() -> None:
    sys.exit(run())
