"""Input reader / normaliser (reference parity: C5, main.c:76-108).

The reference reads whitespace-delimited tokens from stdin with fscanf —
4 weights, Seq1, a count N, then N Seq2 strings — and uppercases them with
(racy) OpenMP loops.  Here parsing is token-based on the whole stream and
normalisation is vectorised in numpy during encoding; the race is designed
out because nothing is shared-mutable.
"""

from __future__ import annotations

import contextlib
import sys
from dataclasses import dataclass, field
from typing import TextIO

import numpy as np

from ..models.encoding import encode_normalized
from ..utils.constants import INT32_MIN


class InputFormatError(ValueError):
    """Raised when stdin does not follow the A.4 input contract."""


@dataclass
class Problem:
    """One batch scoring problem (the program's entire runtime config, A.4).

    Carries both the raw text and the integer encodings: sequences are
    normalised+encoded exactly once, at parse time.
    """

    weights: list[int]
    seq1: str
    seq2: list[str] = field(default_factory=list)
    seq1_codes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int8))
    seq2_codes: list[np.ndarray] = field(default_factory=list)

    @property
    def num_seq2(self) -> int:
        return len(self.seq2)


def _parse_header_tokens(head: list[str]) -> tuple[list[int], str, int]:
    """Validate the 6 header tokens: 4 weights, Seq1, N."""
    if len(head) < 6:
        raise InputFormatError(
            "input too short: expected 'w1 w2 w3 w4  Seq1  N  Seq2...'"
        )
    try:
        weights = [int(t) for t in head[:4]]
    except ValueError as e:
        raise InputFormatError(f"bad weight token: {e}") from e
    for w in weights:
        # The reference reads weights as C int (main.c:76); out-of-range
        # values must fail here, not as an opaque overflow downstream.
        # INT32_MIN itself is excluded: weights w2..w4 are negated into an
        # int32 table (values.signed_weights), and -INT32_MIN overflows.
        if not INT32_MIN < w < 2**31:
            raise InputFormatError(f"weight {w} outside 32-bit integer range")
    seq1 = head[4]
    try:
        n = int(head[5])
    except ValueError as e:
        raise InputFormatError(f"bad sequence count token {head[5]!r}") from e
    if n < 0:
        raise InputFormatError(f"negative sequence count {n}")
    return weights, seq1, n


def parse_problem(stream: TextIO) -> Problem:
    """Parse the reference stdin format into a Problem."""
    tokens = stream.read().split()
    weights, seq1, n = _parse_header_tokens(tokens[:6])
    seqs = tokens[6 : 6 + n]
    if len(seqs) != n:
        raise InputFormatError(
            f"declared {n} sequences but found {len(seqs)}"
        )
    # Encode once here: validates characters early (fail-stop before any
    # device work, §5) and hands ready-to-pad code arrays downstream.
    seq1_codes = encode_normalized(seq1)
    seq2_codes = [encode_normalized(s) for s in seqs]
    return Problem(
        weights=weights,
        seq1=seq1,
        seq2=list(seqs),
        seq1_codes=seq1_codes,
        seq2_codes=seq2_codes,
    )


def load_problem(path: str | None = None) -> Problem:
    """Load a problem from a file path, or stdin when path is None/'-'."""
    with open_input(path) as f:
        return parse_problem(f)


@contextlib.contextmanager
def open_input(path: str | None = None):
    """Context manager yielding the input stream (stdin for None/'-').

    The streaming parse holds the stream open across the whole scoring
    loop, so callers need the handle, not a fully-read Problem.
    """
    if path is None or path == "-":
        yield sys.stdin
    else:
        with open(path, "r", encoding="ascii") as f:
            yield f


# ---- streaming parse (the --stream pipeline's input side) -----------------


def _iter_tokens(stream: TextIO, bufsize: int = 1 << 20):
    """Yield whitespace-delimited tokens without reading the whole stream."""
    leftover = ""
    while True:
        block = stream.read(bufsize)
        if not block:
            if leftover:
                yield leftover
            return
        if leftover:
            block = leftover + block
        parts = block.split()
        # A block ending mid-token holds that token back for the next read.
        leftover = parts.pop() if parts and not block[-1].isspace() else ""
        yield from parts


@dataclass
class StreamHeader:
    """Parsed header of a streaming problem; Seq2s are pulled on demand.

    The reference reads the whole batch before computing (main.c:96-108).
    Streaming keeps host memory bounded by the chunk size and lets the CLI
    overlap parsing chunk i+1 with device compute on chunk i — the host-IO
    / device-compute pipelining tier (SURVEY §2.4 PP row).
    """

    weights: list[int]
    seq1: str
    seq1_codes: np.ndarray
    num_seq2: int
    _tokens: object  # token iterator positioned at the first Seq2

    def iter_chunks(self, chunk_size: int):
        """Yield ``(start_index, [seq2_codes...])`` of <= chunk_size
        sequences each, encoding (and validating) lazily.  Raises
        InputFormatError if the stream ends before ``num_seq2`` sequences.
        """
        if chunk_size < 1:
            raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
        done = 0
        while done < self.num_seq2:
            take = min(chunk_size, self.num_seq2 - done)
            codes: list[np.ndarray] = []
            for _ in range(take):
                tok = next(self._tokens, None)
                if tok is None:
                    raise InputFormatError(
                        f"declared {self.num_seq2} sequences but stream "
                        f"ended at {done + len(codes)}"
                    )
                codes.append(encode_normalized(tok))
            yield done, codes
            done += take


def parse_stream_header(stream: TextIO) -> StreamHeader:
    """Parse weights/Seq1/N and return a header whose chunks stream."""
    tokens = _iter_tokens(stream)
    head = [t for _, t in zip(range(6), tokens)]
    weights, seq1, n = _parse_header_tokens(head)
    return StreamHeader(
        weights=weights,
        seq1=seq1,
        seq1_codes=encode_normalized(seq1),
        num_seq2=n,
        _tokens=tokens,
    )
