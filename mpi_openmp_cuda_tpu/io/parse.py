"""Input reader / normaliser (reference parity: C5, main.c:76-108).

The reference reads whitespace-delimited tokens from stdin with fscanf —
4 weights, Seq1, a count N, then N Seq2 strings — and uppercases them with
(racy) OpenMP loops.  Here parsing is token-based on the whole stream and
normalisation is vectorised in numpy during encoding; the race is designed
out because nothing is shared-mutable.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import TextIO

import numpy as np

from ..models.encoding import encode_normalized
from ..utils.constants import INT32_MIN


class InputFormatError(ValueError):
    """Raised when stdin does not follow the A.4 input contract."""


@dataclass
class Problem:
    """One batch scoring problem (the program's entire runtime config, A.4).

    Carries both the raw text and the integer encodings: sequences are
    normalised+encoded exactly once, at parse time.
    """

    weights: list[int]
    seq1: str
    seq2: list[str] = field(default_factory=list)
    seq1_codes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int8))
    seq2_codes: list[np.ndarray] = field(default_factory=list)

    @property
    def num_seq2(self) -> int:
        return len(self.seq2)


def parse_problem(stream: TextIO) -> Problem:
    """Parse the reference stdin format into a Problem."""
    tokens = stream.read().split()
    if len(tokens) < 6:
        raise InputFormatError(
            "input too short: expected 'w1 w2 w3 w4  Seq1  N  Seq2...'"
        )
    try:
        weights = [int(t) for t in tokens[:4]]
    except ValueError as e:
        raise InputFormatError(f"bad weight token: {e}") from e
    for w in weights:
        # The reference reads weights as C int (main.c:76); out-of-range
        # values must fail here, not as an opaque overflow downstream.
        # INT32_MIN itself is excluded: weights w2..w4 are negated into an
        # int32 table (values.signed_weights), and -INT32_MIN overflows.
        if not INT32_MIN < w < 2**31:
            raise InputFormatError(f"weight {w} outside 32-bit integer range")
    seq1 = tokens[4]
    try:
        n = int(tokens[5])
    except ValueError as e:
        raise InputFormatError(f"bad sequence count token {tokens[5]!r}") from e
    if n < 0:
        raise InputFormatError(f"negative sequence count {n}")
    seqs = tokens[6 : 6 + n]
    if len(seqs) != n:
        raise InputFormatError(
            f"declared {n} sequences but found {len(seqs)}"
        )
    # Encode once here: validates characters early (fail-stop before any
    # device work, §5) and hands ready-to-pad code arrays downstream.
    seq1_codes = encode_normalized(seq1)
    seq2_codes = [encode_normalized(s) for s in seqs]
    return Problem(
        weights=weights,
        seq1=seq1,
        seq2=list(seqs),
        seq1_codes=seq1_codes,
        seq2_codes=seq2_codes,
    )


def load_problem(path: str | None = None) -> Problem:
    """Load a problem from a file path, or stdin when path is None/'-'."""
    if path is None or path == "-":
        return parse_problem(sys.stdin)
    with open(path, "r", encoding="ascii") as f:
        return parse_problem(f)
