"""Result printer (reference parity: C8, main.c:199-211).

Byte-identical output contract: one line per Seq2, in input order:
``#i: score: S, n: N, k: K``.
"""

from __future__ import annotations

import json
import sys
from typing import Iterable, Sequence, TextIO


def format_result(i: int, score: int, n: int, k: int) -> str:
    return f"#{i}: score: {score}, n: {n}, k: {k}"


def print_results(
    results: Iterable[Sequence[int]], out: TextIO | None = None
) -> None:
    out = out or sys.stdout
    for i, (score, n, k) in enumerate(results):
        print(format_result(i, int(score), int(n), int(k)), file=out)


def write_json_sidecar(
    results: Iterable[Sequence[int]], path: str, meta: dict | None = None
) -> None:
    """Optional structured sidecar (§5 observability); stdout stays canonical."""
    payload = {
        "results": [
            {"index": i, "score": int(s), "n": int(n), "k": int(k)}
            for i, (s, n, k) in enumerate(results)
        ],
    }
    if meta:
        payload["meta"] = meta
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
