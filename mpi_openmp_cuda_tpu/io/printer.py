"""Result printer (reference parity: C8, main.c:199-211).

Byte-identical output contract: one line per Seq2, in input order:
``#i: score: S, n: N, k: K``.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
from typing import Iterable, Sequence, TextIO


def format_result(i: int, score: int, n: int, k: int) -> str:
    return f"#{i}: score: {score}, n: {n}, k: {k}"


def print_results(
    results: Iterable[Sequence[int]],
    out: TextIO | None = None,
    start: int = 0,
) -> None:
    """``start`` offsets the printed indices — the streaming pipeline
    prints chunk by chunk while keeping global input-order numbering."""
    out = out or sys.stdout
    for i, (score, n, k) in enumerate(results, start=start):
        print(format_result(i, int(score), int(n), int(k)), file=out)


@contextlib.contextmanager
def guarded_stdout():
    """Protect the result stream from native-library chatter.

    Multi-process collective backends can write status lines directly to
    file descriptor 1 from C++ (e.g. Gloo's peer-connection banner on the
    CPU backend), interleaving with — and corrupting — the byte-exact
    result contract.  This redirects fd 1 to stderr for the duration and
    yields a stream on a private duplicate of the real stdout, so only
    deliberate result printing reaches it.
    """
    sys.stdout.flush()
    saved = os.dup(1)
    try:
        real_stdout = os.fdopen(saved, "w")
    except OSError:
        os.close(saved)
        raise
    try:
        os.dup2(2, 1)
        yield real_stdout
    finally:
        # fd 1 must be restored even if flushing raises (e.g. BrokenPipeError
        # when the consumer of the real stdout has gone away).
        try:
            real_stdout.flush()
            sys.stdout.flush()
        finally:
            os.dup2(saved, 1)
            real_stdout.close()  # closes the dup; fd 1 is restored above


def write_json_sidecar(
    results: Iterable[Sequence[int]], path: str, meta: dict | None = None
) -> None:
    """Optional structured sidecar (§5 observability); stdout stays canonical."""
    payload = {
        "results": [
            {"index": i, "score": int(s), "n": int(n), "k": int(k)}
            for i, (s, n, k) in enumerate(results)
        ],
    }
    if meta:
        payload["meta"] = meta
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
